"""Fleet observatory (obs/fleetobs, tracectx, slo, traceexport; ISSUE 17).

What must hold, layer by layer:

- **Trace context**: the four-field context survives its wire form
  exactly, ``child()`` advances only the hop, and ``KTPU_FLEET_TRACE=0``
  turns minting into a no-op everywhere downstream.
- **FileBus compaction**: a size-capped topic log drops its oldest
  complete lines behind a base-offset header; a live subscriber's held
  offset keeps meaning the same bytes across rotations, a subscriber
  parked before the base resumes at the oldest surviving line, and each
  rotation is counted under ``ktpu_fleet_bus_rotations_total``.
- **SLO tracker**: burn rate = window bad-fraction / (1 - target), per
  window, on an injectable clock; events age out of the short window
  while the long one still remembers them; the gauges export.
- **Trace export**: the emitted Chrome-trace document survives a JSON
  round-trip, ``validate()`` passes it, and ``validate()`` CATCHES a
  round slice whose segment table no longer sums to its wall — the
  waterfall exactness invariant re-checked on the export alone.
- **Stitching**: ``round_counts`` counts original local work only
  (remote echoes and adoption replays are views, not rounds), and
  snapshot solves get the same deduped problem capsules resident rounds
  do.
"""

import json

import pytest

from karpenter_tpu.controllers.provisioning import TPUScheduler
from karpenter_tpu.fleet.bus import FileBus
from karpenter_tpu.obs import fleetobs, tracectx, traceexport
from karpenter_tpu.obs import ledger as obs_ledger
from karpenter_tpu.obs.slo import SLOTracker
from karpenter_tpu.utils.metrics import (
    FLEET_BUS_ROTATIONS,
    SLO_BURN_RATE,
)

from test_resident import kind_pods, make_templates


class TestTraceContext:
    def test_wire_round_trip_and_child_hop(self):
        ctx = tracectx.mint(origin="rep-a", tenant="team-blue")
        assert ctx is not None and len(ctx.trace_id) == 16 and ctx.hop == 0
        back = tracectx.TraceContext.from_wire(ctx.to_wire())
        assert back == ctx
        kid = ctx.child()
        assert (kid.trace_id, kid.origin, kid.tenant) == (
            ctx.trace_id, ctx.origin, ctx.tenant,
        )
        assert kid.hop == 1
        assert tracectx.TraceContext.from_dict(kid.as_dict()) == kid

    def test_malformed_wire_forms_are_none(self):
        for raw in ("", "a|b", "|origin|tenant|0", "a|b|c|d|e", None):
            assert tracectx.TraceContext.from_wire(raw) is None
        # a junk hop degrades to 0 rather than raising mid-RPC
        assert tracectx.TraceContext.from_wire("id|o|t|junk").hop == 0

    def test_activation_scopes_and_disable_knob(self, monkeypatch):
        assert tracectx.current() is None
        ctx = tracectx.mint(origin="rep-a")
        with tracectx.activate(ctx):
            assert tracectx.current() is ctx
            assert tracectx.current_dict() == ctx.as_dict()
        assert tracectx.current() is None
        monkeypatch.setenv("KTPU_FLEET_TRACE", "0")
        assert tracectx.mint(origin="rep-a") is None
        with tracectx.activate(None) as got:
            assert got is None and tracectx.current() is None


class TestFileBusCompaction:
    def test_capped_log_compacts_and_live_readers_keep_up(self, tmp_path):
        """Publish past the cap: the oldest lines go, the rotation is
        counted, and a subscriber that pumps between publishes (the
        FleetMember cadence — once per solve round) sees every message
        exactly once, in order, because its offset is a LOGICAL stream
        position that survives the rewrites."""
        bus = FileBus(str(tmp_path), max_bytes=600)
        rot0 = FLEET_BUS_ROTATIONS.get(topic="session")
        got, offset = [], 0
        for n in range(20):
            bus.publish("session", {"n": n, "pad": "x" * 60})
            msgs, offset = bus.fetch("session", offset)
            got.extend(m["n"] for m in msgs)
        assert got == list(range(20))
        assert FLEET_BUS_ROTATIONS.get(topic="session") > rot0
        # a reader parked before the base lost the compacted prefix but
        # resumes cleanly at the oldest SURVIVING line — never mid-line,
        # never a duplicate
        msgs, _ = bus.fetch("session", 0)
        ns = [m["n"] for m in msgs]
        assert ns == sorted(set(ns)) and ns[-1] == 19 and ns[0] > 0
        # the surviving file is actually bounded near the cap
        assert (tmp_path / "session.jsonl").stat().st_size <= 600 + 100

    def test_header_is_invisible_to_message_consumers(self, tmp_path):
        bus = FileBus(str(tmp_path), max_bytes=300)
        for n in range(30):
            bus.publish("audit", {"n": n, "pad": "y" * 40})
        raw = (tmp_path / "audit.jsonl").read_bytes()
        assert raw.startswith(b"#"), "compaction must leave a base header"
        msgs, _ = bus.fetch("audit", 0)
        assert msgs and all(isinstance(m["n"], int) for m in msgs)

    def test_env_knob_and_unbounded_default(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KTPU_BUS_MAX_BYTES", "512")
        assert FileBus(str(tmp_path / "a"))._max_bytes == 512
        monkeypatch.delenv("KTPU_BUS_MAX_BYTES")
        big = FileBus(str(tmp_path / "b"))
        assert big._max_bytes == 0
        for n in range(50):
            big.publish("compile", {"n": n, "pad": "z" * 50})
        msgs, _ = big.fetch("compile", 0)
        assert [m["n"] for m in msgs] == list(range(50))


class TestSLOTracker:
    def test_burn_rate_is_bad_fraction_over_budget(self):
        t = [0.0]
        slo = SLOTracker(target=0.9, latency_s=1.0, clock=lambda: t[0])
        for i in range(10):
            slo.observe_availability(i != 0)  # 1 bad in 10, 10% budget
        rates = slo.burn_rates()
        cell = rates["availability"]["5m"]
        assert (cell["total"], cell["bad"]) == (10, 1)
        assert cell["burn_rate"] == pytest.approx(1.0)
        assert rates["availability"]["1h"]["burn_rate"] == pytest.approx(1.0)
        # 2x the budget -> burn 2.0, and the long-window budget is gone
        slo.observe_availability(False)
        assert slo.burn_rates()["availability"]["5m"]["burn_rate"] > 1.5
        assert slo.budget_remaining()["availability"] == 0.0

    def test_short_window_forgets_while_long_remembers(self):
        t = [0.0]
        slo = SLOTracker(target=0.99, latency_s=1.0, clock=lambda: t[0])
        slo.observe_latency(5.0)  # bad
        t[0] = 200.0
        for _ in range(3):
            slo.observe_latency(0.1)
        t[0] = 400.0  # the bad event is now outside 5m but inside 1h
        rates = slo.burn_rates()
        assert rates["latency"]["5m"]["bad"] == 0
        assert rates["latency"]["1h"]["bad"] == 1

    def test_observe_record_folds_both_objectives(self):
        t = [0.0]
        slo = SLOTracker(target=0.99, latency_s=0.5, clock=lambda: t[0])
        slo.observe_record({"wall_s": 0.1, "outcome": "ok"})
        slo.observe_record({"wall_s": 2.0, "outcome": "ok"})  # slow but up
        slo.observe_record({"wall_s": 0.1, "outcome": "error"})
        slo.observe_record(
            {"wall_s": 0.1, "outcome": "ok", "mode": "quarantined"}
        )
        rates = slo.burn_rates()
        assert rates["latency"]["5m"]["bad"] == 1
        assert rates["availability"]["5m"]["bad"] == 2
        # snapshot re-exports the gauges every time it is asked
        slo.snapshot()
        assert SLO_BURN_RATE.get(objective="latency", window="5m") == (
            rates["latency"]["5m"]["burn_rate"]
        )

    def test_reconfigure_reads_env_and_reset_clears(self, monkeypatch):
        monkeypatch.setenv("KTPU_SLO_TARGET", "0.95")
        monkeypatch.setenv("KTPU_SLO_LATENCY_S", "0.25")
        slo = SLOTracker(clock=lambda: 0.0)
        assert (slo.target, slo.latency_s) == (0.95, 0.25)
        monkeypatch.setenv("KTPU_SLO_TARGET", "2.0")  # clamped to sane
        slo.reconfigure()
        assert slo.target <= 0.9999
        slo.observe_availability(False)
        slo.reset()
        assert slo.burn_rates()["availability"]["5m"]["total"] == 0


def _rec(replica, seq, t, sig, trace, *, wall=0.02, replay=False,
         source="local", waterfall=None):
    rec = {
        "replica": replica, "seq": seq, "t": t, "sig": sig,
        "trace": trace, "wall_s": wall, "mode": "delta", "reason": "arrivals",
        "outcome": "ok", "pods": 8, "source": source,
    }
    if replay:
        rec["replay"] = True
    if waterfall is not None:
        rec["waterfall"] = waterfall
    return rec


def _handoff_records():
    """Three rounds on rep-a, the third handed off: its replay lands on
    rep-b under the SAME trace id one hop further along."""
    t1 = {"id": "aaaa000011112222", "origin": "client-1", "tenant": "", "hop": 1}
    t2 = {"id": "bbbb000011112222", "origin": "client-1", "tenant": "", "hop": 1}
    wf = {
        "wall_s": 0.02,
        "segments": {"encode": 0.005, "device": 0.01, "other": 0.005},
        "spans": {
            "name": ["encode", "device"],
            "start_s": [0.0, 0.005],
            "dur_s": [0.005, 0.01],
            "depth": [0, 0],
        },
    }
    return [
        _rec("rep-a", 1, 100.0, "sig-1", t1, waterfall=wf),
        _rec("rep-a", 2, 100.1, "sig-2", t1),
        _rec("rep-a", 3, 100.2, "sig-3", t2),
        _rec("rep-b", 4, 100.5, "sig-3", dict(t2, hop=3), replay=True),
        _rec("rep-b", 5, 100.6, "sig-4", dict(t2, hop=3)),
    ]


class TestStitching:
    def test_round_counts_ignore_replays_and_remote_echoes(self):
        recs = _handoff_records()
        recs.append(_rec("client", 9, 100.7, "sig-4", None, source="remote"))
        counts = fleetobs.round_counts(recs)
        assert counts == {"sig-1": 1, "sig-2": 1, "sig-3": 1, "sig-4": 1}
        # a genuine duplicate (the same original round recorded twice)
        # IS flagged — that is the invariant's whole point
        recs.append(_rec("rep-b", 10, 100.8, "sig-4", None))
        assert fleetobs.round_counts(recs)["sig-4"] == 2

    def test_stitch_spans_replicas_and_reports_consistency(self):
        recs = _handoff_records()
        stitched = fleetobs.stitch("bbbb000011112222", recs)
        assert stitched["replicas"] == ["rep-a", "rep-b"]
        assert stitched["max_hop"] == 3 and stitched["replays"] == 1
        assert stitched["consistent"]
        assert len(stitched["rounds"]) == 3
        assert fleetobs.stitch("nope", recs) is None
        # the OTHER trace never left rep-a
        assert fleetobs.stitch("aaaa000011112222", recs)["replicas"] == ["rep-a"]

    def test_fleet_summary_rolls_up_per_replica(self):
        recs = _handoff_records()
        summary = fleetobs.fleet_summary(recs)
        assert summary["records"] == 5 and summary["traces"] == 2
        assert summary["replicas"]["rep-a"]["rounds"] == 3
        assert summary["replicas"]["rep-b"]["replays"] == 1
        assert summary["duplicate_rounds"] == {}
        assert "burn_rates" in summary["slo"]

    def test_spilled_dirs_merge_and_dedup(self, tmp_path):
        """A peer's spilled JSONL joins the timeline; a record seen both
        spilled and in-ring collapses to one entry by (replica, seq, t)."""
        recs = _handoff_records()
        with open(tmp_path / "rounds.jsonl", "w") as fh:
            for r in recs + recs[:2]:  # spill carries duplicates too
                fh.write(json.dumps(r) + "\n")
        merged = fleetobs.fleet_records(dirs=[str(tmp_path)])
        keys = [(r.get("replica"), r.get("seq")) for r in merged]
        assert len(keys) == len(set(keys))
        assert ("rep-b", 4) in keys

    def test_telemetry_frame_keeps_wire_keys_only(self):
        rec = _handoff_records()[0]
        rec["stages"] = {"scan": 0.001}
        rec["transcript"] = [["u1", "u2"]]
        frame = obs_ledger.telemetry_frame(rec)
        assert frame["sig"] == "sig-1" and frame["seq"] == 1
        assert frame["trace"]["id"] == "aaaa000011112222"
        assert "transcript" not in frame and "stages" not in frame
        assert obs_ledger.telemetry_frame("junk") is None


class TestTraceExport:
    def test_export_round_trips_and_validates(self):
        doc = traceexport.chrome_trace(_handoff_records())
        doc = json.loads(json.dumps(doc))  # the schema round-trip
        assert traceexport.validate(doc) == []
        events = doc["traceEvents"]
        procs = [e for e in events if e.get("name") == "process_name"]
        assert {p["args"]["name"] for p in procs} == {
            "replica rep-a", "replica rep-b",
        }
        rounds = [e for e in events if e.get("cat") == "round"]
        assert len(rounds) == 5
        assert any(r["args"].get("replay") for r in rounds)
        spans = [e for e in events if e.get("cat") == "span"]
        assert {s["name"] for s in spans} == {"encode", "device"}
        # the handoff drew exactly one flow arrow, start and finish paired
        flows = [e for e in events if e.get("cat") == "flow"]
        assert sorted(e["ph"] for e in flows) == ["f", "s"]
        assert flows[0]["id"] == flows[1]["id"]

    def test_validate_catches_a_broken_waterfall_invariant(self):
        doc = traceexport.chrome_trace(_handoff_records())
        for ev in doc["traceEvents"]:
            if (ev.get("args") or {}).get("segments"):
                ev["args"]["segments"]["device"] += 0.5  # sum != wall now
        problems = traceexport.validate(doc)
        assert problems and "segments sum" in problems[0]

    def test_validate_catches_unpaired_flows_and_bad_slices(self):
        doc = traceexport.chrome_trace(_handoff_records())
        doc["traceEvents"] = [
            e for e in doc["traceEvents"] if e.get("ph") != "f"
        ]
        assert any("unpaired" in p for p in traceexport.validate(doc))
        assert traceexport.validate({"traceEvents": [{"no": "phase"}]})
        assert traceexport.validate({"traceEvents": None})

    def test_export_trace_stitches_one_id(self):
        recs = _handoff_records()
        doc = traceexport.export_trace("bbbb000011112222", recs)
        rounds = [
            e for e in doc["traceEvents"] if e.get("cat") == "round"
        ]
        assert len(rounds) == 3
        assert traceexport.export_trace("nope", recs) is None


class TestLedgerTraceStamping:
    def test_records_mint_a_local_trace_and_replica_stamp(self):
        seq0 = obs_ledger.LEDGER.seq()
        sched = TPUScheduler(make_templates(), max_claims=128)
        sched.solve(list(kind_pods("a", 6)))
        rec = obs_ledger.LEDGER.since(seq0)[-1]
        assert rec["replica"] == obs_ledger.current_replica()
        assert rec["trace"]["id"] and rec["trace"]["hop"] == 0
        assert rec["trace"]["origin"] == rec["replica"]

    def test_replica_scope_wins_over_process_default(self):
        with obs_ledger.replica_scope("rep-x"):
            assert obs_ledger.current_replica() == "rep-x"
            rec = obs_ledger.LEDGER.record({"mode": "full", "outcome": "ok"})
        assert rec["replica"] == "rep-x"
        assert obs_ledger.current_replica().startswith("proc-")

    def test_snapshot_solve_writes_a_deduped_plain_capsule(
        self, monkeypatch, tmp_path
    ):
        """The satellite: non-resident solves get the same problem-capsule
        treatment resident rounds do — spill-gated, content-addressed,
        written once for identical problems."""
        monkeypatch.setenv("KTPU_LEDGER_DIR", str(tmp_path))
        sched = TPUScheduler(make_templates(), max_claims=128)
        pods = kind_pods("a", 6)
        seq0 = obs_ledger.LEDGER.seq()
        sched.solve(list(pods))
        rec = obs_ledger.LEDGER.since(seq0)[-1]
        assert rec["capsule"] and rec["transcript"] == [
            [str(p.uid) for p in pods]
        ]
        capsule_path = tmp_path / rec["capsule"]
        assert capsule_path.exists()
        doc = json.loads(capsule_path.read_text())
        assert doc["path"] == "snapshot"
        assert len(doc["rounds"]) == 1 and len(doc["pods"]) == 6
        stamp = capsule_path.stat().st_mtime_ns
        # the identical problem again: the capsule is NOT rewritten
        sched.solve(list(pods))
        rec2 = obs_ledger.LEDGER.since(seq0)[-1]
        assert rec2["capsule"] == rec["capsule"]
        assert capsule_path.stat().st_mtime_ns == stamp
