"""Disruption engine: emptiness, consolidation, drift, budgets,
orchestration — end-to-end on the kwok harness with a fake clock."""

import numpy as np
import pytest

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import Budget, NodePool
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import FakeClock


def build_env(catalog_size=50, consolidate_after=0.0, policy="WhenEmptyOrUnderutilized"):
    clock = FakeClock()
    store = ObjectStore(clock)
    cloud = KwokCloudProvider(store, catalog=instance_types(catalog_size))
    mgr = Manager(store, cloud, clock)
    pool = NodePool()
    pool.metadata.name = "default"
    pool.spec.disruption.consolidate_after_seconds = consolidate_after
    pool.spec.disruption.consolidation_policy = policy
    # the default 10% budget floors to 0 allowed disruptions on the tiny
    # clusters these tests build (faithful reference behavior); open it up
    pool.spec.disruption.budgets = [Budget(nodes="100%")]
    # pin to on-demand: kwok launches the cheapest (spot) offering, and
    # spot->spot consolidation is feature-gated off per the reference
    pool.spec.template.spec.requirements = [
        {
            "key": l.CAPACITY_TYPE_LABEL_KEY,
            "operator": "In",
            "values": [l.CAPACITY_TYPE_ON_DEMAND],
        }
    ]
    store.create(ObjectStore.NODEPOOLS, pool)
    return clock, store, cloud, mgr


def provision(mgr, store, cloud, pods):
    for p in pods:
        store.create(ObjectStore.PODS, p)
    mgr.run_until_idle()
    cloud.simulate_kubelet_ready()
    mgr.run_until_idle()
    KubeSchedulerSim(store, mgr.cluster).bind_pending()
    mgr.run_until_idle()


def delete_pods(store, mgr, predicate):
    for pod in list(store.pods()):
        if predicate(pod):
            pod.status.phase = "Succeeded"
            store.update(ObjectStore.PODS, pod)
            store.delete(ObjectStore.PODS, pod.name)
    mgr.run_until_idle()


def disrupt_through_validation(mgr, clock, polls=3, step=16.0):
    """First poll stages a command for the 15s validation window
    (emptiness.go:101 — every method validates); later polls execute it."""
    for _ in range(polls):
        cmd = mgr.run_disruption_once()
        if cmd is not None:
            return cmd
        clock.step(step)
    return None


class TestEmptiness:
    def test_empty_nodes_deleted(self):
        clock, store, cloud, mgr = build_env()
        provision(mgr, store, cloud, [make_pod(f"p-{i}", cpu=1.0) for i in range(20)])
        n_before = len(store.nodes())
        assert n_before >= 1
        # all pods finish -> all nodes empty
        delete_pods(store, mgr, lambda p: True)
        clock.step(30.0)
        cmd = disrupt_through_validation(mgr, clock)
        assert cmd is not None and cmd.reason == "Empty"
        mgr.run_until_idle()
        assert len(store.nodes()) < n_before
        assert len(store.nodeclaims()) < n_before

    def test_emptiness_respects_consolidate_after(self):
        clock, store, cloud, mgr = build_env(consolidate_after=300.0)
        provision(mgr, store, cloud, [make_pod("p", cpu=1.0)])
        delete_pods(store, mgr, lambda p: True)
        clock.step(30.0)  # not yet idle long enough
        cmd = mgr.run_disruption_once()
        assert cmd is None
        clock.step(300.0)
        cmd = disrupt_through_validation(mgr, clock)
        assert cmd is not None

    def test_emptiness_budget(self):
        clock, store, cloud, mgr = build_env(catalog_size=8)  # 1-cpu shapes
        pool = store.get(ObjectStore.NODEPOOLS, "default")
        pool.spec.disruption.budgets = [Budget(nodes="1")]
        store.update(ObjectStore.NODEPOOLS, pool)
        provision(mgr, store, cloud, [make_pod(f"p-{i}", cpu=0.5) for i in range(4)])
        n_nodes = len(store.nodes())
        assert n_nodes >= 3
        delete_pods(store, mgr, lambda p: True)
        clock.step(30.0)
        cmd = disrupt_through_validation(mgr, clock)
        assert cmd is not None and len(cmd.candidates) == 1  # budget caps at 1

    def test_emptiness_validated_not_immediate(self):
        """Emptiness waits out the 15s validation delay; a pod binding to
        the 'empty' node during the window cancels the command
        (emptiness.go:101 validator.Validate)."""
        clock, store, cloud, mgr = build_env()
        provision(mgr, store, cloud, [make_pod("p", cpu=1.0)])
        n_before = len(store.nodes())
        delete_pods(store, mgr, lambda p: True)
        clock.step(30.0)
        # first poll only stages the command
        assert mgr.run_disruption_once() is None
        assert len(store.nodes()) == n_before
        # a fresh pod lands on the node during the validation window
        newcomer = make_pod("late", cpu=0.5)
        newcomer.spec.node_name = store.nodes()[0].name
        store.create(ObjectStore.PODS, newcomer)
        mgr.run_until_idle()
        clock.step(16.0)
        assert mgr.run_disruption_once() is None
        assert len(store.nodes()) == n_before, "node deleted under a fresh pod"

    def test_budget_percentage_rounds_up(self):
        # reference rounds percentages UP (nodepool.go:391-396) so pools
        # under 10 nodes still allow one disruption at the default 10%
        assert Budget(nodes="10%").allowed(5) == 1
        assert Budget(nodes="10%").allowed(0) == 0
        assert Budget(nodes="10%").allowed(25) == 3
        assert Budget(nodes="50%").allowed(3) == 2
        assert Budget(nodes="3").allowed(100) == 3


class TestConsolidation:
    def test_underutilized_cluster_consolidates(self):
        """Pods shrink -> many small-occupancy nodes -> consolidation deletes
        or replaces some."""
        clock, store, cloud, mgr = build_env(catalog_size=64)
        pods = [make_pod(f"p-{i}", cpu=1.5, memory="1Gi") for i in range(8)]
        provision(mgr, store, cloud, pods)
        cpu_before = sum(n.status.capacity["cpu"] for n in store.nodes())
        # most pods finish; leave 2
        delete_pods(store, mgr, lambda p: p.name not in ("p-0", "p-1"))
        clock.step(60.0)
        # first poll stages the command for the 15s validation window;
        # subsequent polls validate, execute, and complete orchestration
        executed = None
        for _ in range(8):
            cmd = mgr.run_disruption_once()
            executed = executed or cmd
            cloud.simulate_kubelet_ready()
            mgr.run_until_idle()
            KubeSchedulerSim(store, mgr.cluster).bind_pending()
            clock.step(20.0)
        assert executed is not None, "no disruption command produced"
        # replace-consolidation shrinks capacity (16-cpu -> 4-cpu node)
        cpu_after = sum(n.status.capacity["cpu"] for n in store.nodes())
        assert cpu_after < cpu_before
        assert all(p.spec.node_name for p in store.pods())

    def test_consolidation_keeps_pods_schedulable(self):
        clock, store, cloud, mgr = build_env(catalog_size=64)
        pods = [make_pod(f"p-{i}", cpu=1.5, memory="1Gi") for i in range(6)]
        provision(mgr, store, cloud, pods)
        delete_pods(store, mgr, lambda p: p.name not in ("p-0", "p-1", "p-2"))
        clock.step(60.0)
        for _ in range(6):
            mgr.run_disruption_once()
            cloud.simulate_kubelet_ready()
            mgr.run_until_idle()
            KubeSchedulerSim(store, mgr.cluster).bind_pending()
            clock.step(20.0)
        # drained pods re-provision and re-bind once the churn settles
        for _ in range(4):
            mgr.run_until_idle()
            cloud.simulate_kubelet_ready()
            mgr.run_until_idle()
            KubeSchedulerSim(store, mgr.cluster).bind_pending()
        alive = [p for p in store.pods() if p.name in ("p-0", "p-1", "p-2")]
        assert len(alive) == 3
        for p in alive:
            assert p.spec.node_name, f"{p.name} lost its node"


class TestDrift:
    def test_hash_drift_replaces_node(self):
        clock, store, cloud, mgr = build_env()
        provision(mgr, store, cloud, [make_pod("p", cpu=1.0)])
        claim = store.nodeclaims()[0]
        assert not claim.conditions.is_true("Drifted")
        # operator changes the pool's template labels -> hash changes
        pool = store.get(ObjectStore.NODEPOOLS, "default")
        pool.spec.template.labels["team"] = "new-team"
        store.update(ObjectStore.NODEPOOLS, pool)
        assert mgr.mark_drift() >= 1
        assert store.nodeclaims()[0].conditions.is_true("Drifted")
        clock.step(30.0)
        cmd = mgr.run_disruption_once()  # stages for validation
        assert cmd is None
        clock.step(16.0)
        cmd = mgr.run_disruption_once()  # validates + executes
        assert cmd is not None and cmd.reason == "Drifted"
        # replacement claim created alongside the doomed one
        mgr.run_until_idle()
        assert len(store.nodeclaims()) >= 2

    def test_provider_drift(self):
        clock, store, cloud, mgr = build_env()
        provision(mgr, store, cloud, [make_pod("p", cpu=1.0)])
        claim = store.nodeclaims()[0]
        orig = cloud.is_drifted
        cloud.is_drifted = lambda c: "CloudDrift" if c.name == claim.name else None
        mgr.mark_drift()
        assert store.nodeclaims()[0].conditions.is_true("Drifted")
        cloud.is_drifted = orig


class TestOrchestration:
    def test_do_not_disrupt_blocks(self):
        clock, store, cloud, mgr = build_env()
        pod = make_pod("guarded", cpu=1.0)
        pod.metadata.annotations[l.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        provision(mgr, store, cloud, [pod])
        # even empty-ish nodes with guarded pods are not candidates
        clock.step(60.0)
        cmd = mgr.run_disruption_once()
        assert cmd is None

    def test_candidates_tainted_then_deleted(self):
        clock, store, cloud, mgr = build_env()
        provision(mgr, store, cloud, [make_pod(f"p-{i}", cpu=1.0) for i in range(4)])
        delete_pods(store, mgr, lambda p: True)
        clock.step(30.0)
        cmd = disrupt_through_validation(mgr, clock)
        assert cmd is not None
        # nodes tainted during the window, then deleted once processed
        for _ in range(3):
            mgr.run_disruption_once()
            mgr.run_until_idle()
        assert store.nodes() == []
