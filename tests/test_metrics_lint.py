"""Metric-naming lint: every registered family follows the convention.

PR-2 satellite: new families use the ``ktpu_`` prefix, snake_case names,
and non-empty help text. The pre-existing reference-parity families keep
their reference names (``karpenter_*`` / ``operator_*``) — those are the
point of the parity work — but the set is FROZEN below: adding a new
family under a grandfathered prefix fails this lint, so drift has to be
a conscious edit of the freeze list, not an accident.
"""

import re

from karpenter_tpu.utils.metrics import Histogram, REGISTRY

# The reference-parity families shipped before the ktpu_ convention,
# frozen. New metrics MUST be ktpu_-prefixed (or consciously added here
# with a reference citation in their help text).
GRANDFATHERED = frozenset(
    {
        "karpenter_nodeclaims_created_total",
        "karpenter_nodeclaims_terminated_total",
        "karpenter_nodeclaims_disrupted_total",
        "karpenter_nodes_created_total",
        "karpenter_nodes_terminated_total",
        "karpenter_pods_disruption_initiated_total",
        "karpenter_scheduler_scheduling_duration_seconds",
        "karpenter_scheduler_unschedulable_pods_count",
        "karpenter_solver_host_fallback_total",
        "karpenter_solver_rpc_duration_seconds",
        "karpenter_consolidation_timeouts_total",
        "karpenter_disruption_evaluation_duration_seconds",
        "karpenter_disruption_eligible_nodes",
        "karpenter_nodepool_usage",
        "karpenter_nodepool_limit",
        "karpenter_scheduler_queue_depth",
        "karpenter_scheduler_unfinished_work_seconds",
        "karpenter_scheduler_ignored_pods_count",
        "karpenter_scheduler_pending_pods_by_effective_zone_count",
        "karpenter_pods_state",
        "karpenter_pods_startup_duration_seconds",
        "karpenter_pods_bound_duration_seconds",
        "karpenter_nodes_allocatable",
        "karpenter_nodes_total_pod_requests",
        "karpenter_nodes_utilization_percent",
        "operator_status_condition_count",
        "operator_status_condition_transitions_total",
        "karpenter_cloudprovider_duration_seconds",
        "karpenter_cloudprovider_errors_total",
    }
)

SNAKE = re.compile(r"^[a-z][a-z0-9_]*$")


def _families():
    # importing the modules that register families ensures the walk sees
    # everything (utils.metrics registers all module-level families at
    # import; controllers only observe into them)
    import karpenter_tpu.utils.metrics  # noqa: F401

    return REGISTRY.families()


def test_every_family_is_ktpu_prefixed_or_grandfathered():
    offenders = [
        f.name
        for f in _families()
        if not f.name.startswith("ktpu_") and f.name not in GRANDFATHERED
    ]
    assert not offenders, (
        f"families outside the ktpu_ convention: {offenders}; new metrics "
        "must be ktpu_-prefixed (see tests/test_metrics_lint.py)"
    )


def test_every_family_has_help_text():
    missing = [f.name for f in _families() if not f.help.strip()]
    assert not missing, f"families with empty help text: {missing}"


def test_every_family_is_snake_case():
    bad = [f.name for f in _families() if not SNAKE.match(f.name)]
    assert not bad, f"non-snake_case family names: {bad}"


def test_every_label_is_snake_case():
    bad = [
        (f.name, n)
        for f in _families()
        for n in f.label_names
        if not SNAKE.match(n)
    ]
    assert not bad, f"non-snake_case label names: {bad}"


def test_grandfather_list_is_frozen():
    """The freeze is the point: PR-4's fault/fallback/blackout families
    all landed under ktpu_ — nothing new may sneak into the grandfather
    set without consciously editing BOTH this count and the list."""
    assert len(GRANDFATHERED) == 29, (
        "GRANDFATHERED grew or shrank; new families must be ktpu_-prefixed"
    )


def test_fault_and_degradation_families_are_registered():
    """ISSUE-4 families exist with the documented types and labels (the
    doc/metrics-table satellite's machine-checked half)."""
    from karpenter_tpu.utils.metrics import Counter, Gauge

    fams = {f.name: f for f in _families()}
    expected = {
        "ktpu_fault_injections_total": (Counter, ("point", "mode")),
        "ktpu_solver_fallback_total": (Counter, ("reason",)),
        "ktpu_offering_blackout": (Gauge, ("capacity_type",)),
        "ktpu_stream_recoveries_total": (Counter, ("outcome",)),
        "ktpu_stream_stale_frames_total": (Counter, ()),
        "ktpu_transient_retries_total": (Counter, ("controller",)),
        "ktpu_circuit_transitions_total": (Counter, ("target", "to")),
    }
    for name, (cls, labels) in expected.items():
        fam = fams.get(name)
        assert fam is not None, f"{name} not registered"
        assert isinstance(fam, cls), (name, type(fam).__name__)
        assert fam.label_names == labels, (name, fam.label_names)
        assert fam.help.strip()


def test_scan_window_and_encode_cache_families_are_registered():
    """ISSUE-5 families: the active-window spill counter and the
    incremental encode cache hit counter, label-free counters with the
    documented names (bench --report-scan and the perf gates read the
    same numbers from last_timings['scan'])."""
    from karpenter_tpu.utils.metrics import Counter

    fams = {f.name: f for f in _families()}
    for name in (
        "ktpu_scan_window_spills_total",
        "ktpu_encode_cache_hits_total",
    ):
        fam = fams.get(name)
        assert fam is not None, f"{name} not registered"
        assert isinstance(fam, Counter), (name, type(fam).__name__)
        assert fam.label_names == ()
        assert fam.help.strip()


def test_resident_solver_families_are_registered():
    """ISSUE-7 families: resident-session round modes, per-delta pod-count
    histogram, and the kind-scan capacity-grid update counter, with the
    documented types and labels."""
    from karpenter_tpu.utils.metrics import Counter

    fams = {f.name: f for f in _families()}
    expected = {
        "ktpu_resident_rounds_total": (Counter, ("mode",)),
        "ktpu_resident_delta_pods": (Histogram, ()),
        "ktpu_kscan_grid_updates_total": (Counter, ("mode",)),
    }
    for name, (cls, labels) in expected.items():
        fam = fams.get(name)
        assert fam is not None, f"{name} not registered"
        assert isinstance(fam, cls), (name, type(fam).__name__)
        assert fam.label_names == labels, (name, fam.label_names)
        assert fam.help.strip()


def test_shard_families_are_registered():
    """ISSUE-8 families: dp-shard merge outcomes and the replicated-bytes
    estimate, with the documented types and labels (bench --report-shard
    and last_timings['shard'] carry the same numbers)."""
    from karpenter_tpu.utils.metrics import Counter, Gauge

    fams = {f.name: f for f in _families()}
    expected = {
        "ktpu_shard_merge_rounds_total": (Counter, ("outcome", "family")),
        "ktpu_shard_replicated_bytes": (Gauge, ()),
        "ktpu_shard_verdict_bytes_total": (Counter, ()),
        "ktpu_shard_family_eligible_total": (
            Counter,
            ("family", "path", "reason"),
        ),
    }
    for name, (cls, labels) in expected.items():
        fam = fams.get(name)
        assert fam is not None, f"{name} not registered"
        assert isinstance(fam, cls), (name, type(fam).__name__)
        assert fam.label_names == labels, (name, fam.label_names)
        assert fam.help.strip()
    # ISSUE 14 widened the speculation family vocabulary; the help text
    # must document the full label set so dashboards don't guess
    merge_help = fams["ktpu_shard_merge_rounds_total"].help
    for fam_name in ("fill", "existing", "topo_fill", "kscan", "perpod"):
        assert fam_name in merge_help, fam_name
        assert fam_name in fams["ktpu_shard_family_eligible_total"].help
    # ISSUE 20 made the sequential routing self-describing: the help text
    # must name every reason value the eligibility gates can emit
    eligible_help = fams["ktpu_shard_family_eligible_total"].help
    for reason in (
        "no_pipeline", "no_dp_mesh", "shard_dp_off", "kscan_optout",
        "perpod_optout", "quarantined", "existing_optout", "single_group",
        "single_chunk", "gang_atomic",
    ):
        assert reason in eligible_help, reason


def test_guard_families_are_registered():
    """ISSUE-10 families: shadow-audit verdicts, the per-path quarantine
    breaker state, and watchdog-detected dispatch stalls."""
    from karpenter_tpu.utils.metrics import Counter, Gauge

    fams = {f.name: f for f in _families()}
    expected = {
        "ktpu_guard_audits_total": (Counter, ("path", "verdict")),
        "ktpu_guard_quarantined": (Gauge, ("path",)),
        "ktpu_watchdog_stalls_total": (Counter, ("section",)),
    }
    for name, (cls, labels) in expected.items():
        fam = fams.get(name)
        assert fam is not None, f"{name} not registered"
        assert isinstance(fam, cls), (name, type(fam).__name__)
        assert fam.label_names == labels, (name, fam.label_names)
        assert fam.help.strip()


def test_observability_families_are_registered():
    """ISSUE-12 families: round-ledger appends, jit compile attribution +
    retrace storms, and the quarantine TTL gauge behind /debug/quarantine."""
    from karpenter_tpu.utils.metrics import Counter, Gauge

    fams = {f.name: f for f in _families()}
    expected = {
        "ktpu_guard_quarantine_ttl_seconds": (Gauge, ("path",)),
        "ktpu_ledger_rounds_total": (Counter, ("source",)),
        "ktpu_jit_compiles_total": (Counter, ("kernel",)),
        "ktpu_jit_compile_seconds": (Histogram, ()),
        "ktpu_jit_retrace_storms_total": (Counter, ("kernel",)),
    }
    for name, (cls, labels) in expected.items():
        fam = fams.get(name)
        assert fam is not None, f"{name} not registered"
        assert isinstance(fam, cls), (name, type(fam).__name__)
        assert fam.label_names == labels, (name, fam.label_names)
        assert fam.help.strip()


def test_waterfall_families_are_registered():
    """ISSUE-15 families: the per-round critical-path segment histogram
    (obs/waterfall.py) and the dp-row utilization gauge, with the
    documented types and labels. The segment histogram's help must name
    the reconciled 'other' remainder — it is the instrument's whole
    point — and the utilization gauge's help must enumerate its states."""
    from karpenter_tpu.utils.metrics import Gauge

    fams = {f.name: f for f in _families()}
    expected = {
        "ktpu_round_segment_seconds": (Histogram, ("segment",)),
        "ktpu_shard_dp_utilization": (Gauge, ("state",)),
    }
    for name, (cls, labels) in expected.items():
        fam = fams.get(name)
        assert fam is not None, f"{name} not registered"
        assert isinstance(fam, cls), (name, type(fam).__name__)
        assert fam.label_names == labels, (name, fam.label_names)
        assert fam.help.strip()
    assert "other" in fams["ktpu_round_segment_seconds"].help
    for state in ("committed", "replayed", "idle"):
        assert state in fams["ktpu_shard_dp_utilization"].help, state


def test_fleet_families_are_registered():
    """ISSUE-16 families: registry evictions, load shedding, session
    handoffs, the guardrail bus, client retargeting, and the compile
    warmth announcements. The handoff counter's help must enumerate its
    outcome vocabulary — dashboards alert on the non-adopted outcomes —
    and the bus counter's help must name its topics and directions."""
    from karpenter_tpu.utils.metrics import Counter

    fams = {f.name: f for f in _families()}
    expected = {
        "ktpu_rpc_session_evictions_total": (Counter, ("reason",)),
        "ktpu_fleet_shed_total": (Counter, ("reason",)),
        "ktpu_fleet_handoffs_total": (Counter, ("outcome",)),
        "ktpu_fleet_bus_messages_total": (Counter, ("topic", "direction")),
        "ktpu_fleet_retargets_total": (Counter, ("reason",)),
        "ktpu_fleet_warm_announced_total": (Counter, ("kernel",)),
    }
    for name, (cls, labels) in expected.items():
        fam = fams.get(name)
        assert fam is not None, f"{name} not registered"
        assert isinstance(fam, cls), (name, type(fam).__name__)
        assert fam.label_names == labels, (name, fam.label_names)
        assert fam.help.strip()
    for outcome in (
        "adopted",
        "no_capsule",
        "fingerprint_mismatch",
        "replay_failed",
        "shape_mismatch",
    ):
        assert outcome in fams["ktpu_fleet_handoffs_total"].help, outcome
    for word in ("quarantine", "audit", "session", "compile", "published", "received"):
        assert word in fams["ktpu_fleet_bus_messages_total"].help, word


def test_fleet_observatory_families_are_registered():
    """ISSUE-17 families: the SLO burn-rate instruments and the FileBus
    compaction counter. The burn-rate gauge's help must explain the
    burn-rate convention (1.0 = burning the budget exactly at the
    objective's edge) and name the error-budget knob; the events
    counter's help must enumerate both objectives."""
    from karpenter_tpu.utils.metrics import Counter, Gauge

    fams = {f.name: f for f in _families()}
    expected = {
        "ktpu_fleet_bus_rotations_total": (Counter, ("topic",)),
        "ktpu_slo_events_total": (Counter, ("objective", "outcome")),
        "ktpu_slo_burn_rate": (Gauge, ("objective", "window")),
        "ktpu_slo_error_budget_remaining": (Gauge, ("objective",)),
    }
    for name, (cls, labels) in expected.items():
        fam = fams.get(name)
        assert fam is not None, f"{name} not registered"
        assert isinstance(fam, cls), (name, type(fam).__name__)
        assert fam.label_names == labels, (name, fam.label_names)
        assert fam.help.strip()
    assert "KTPU_SLO_TARGET" in fams["ktpu_slo_burn_rate"].help
    assert "1.0" in fams["ktpu_slo_burn_rate"].help
    for objective in ("latency", "availability"):
        assert objective in fams["ktpu_slo_events_total"].help, objective
    assert "KTPU_BUS_MAX_BYTES" in fams["ktpu_fleet_bus_rotations_total"].help


def test_objective_families_are_registered():
    """ISSUE-19 families: K-variant objective round outcomes, the
    canonical-vs-perturbed winner split, and the missing-price counter
    behind the consolidation cost-ranking exclusion. The round counter's
    help must explain both outcomes; the pricing counter's help must say
    missing prices are EXCLUDED from cost ordering, not priced 0.0."""
    from karpenter_tpu.utils.metrics import Counter

    fams = {f.name: f for f in _families()}
    expected = {
        "ktpu_objective_rounds_total": (Counter, ("policy", "outcome")),
        "ktpu_objective_variant_wins_total": (Counter, ("policy", "variant")),
        "ktpu_pricing_missing_total": (Counter, ()),
    }
    for name, (cls, labels) in expected.items():
        fam = fams.get(name)
        assert fam is not None, f"{name} not registered"
        assert isinstance(fam, cls), (name, type(fam).__name__)
        assert fam.label_names == labels, (name, fam.label_names)
        assert fam.help.strip()
    for outcome in ("committed", "replayed"):
        assert outcome in fams["ktpu_objective_rounds_total"].help, outcome
    for variant in ("canonical", "perturbed"):
        assert variant in fams["ktpu_objective_variant_wins_total"].help, variant
    for word in ("EXCLUDED", "0.0"):
        assert word in fams["ktpu_pricing_missing_total"].help, word


def test_counters_end_in_total_and_histograms_in_seconds_or_pods():
    """Unit-suffix discipline for NEW families (grandfathered names keep
    their reference spellings verbatim)."""
    from karpenter_tpu.utils.metrics import Counter

    bad = []
    for f in _families():
        if f.name in GRANDFATHERED:
            continue
        if isinstance(f, Counter) and not f.name.endswith("_total"):
            bad.append(f.name)
        if isinstance(f, Histogram) and not f.name.endswith(
            ("_seconds", "_pods", "_bytes")
        ):
            bad.append(f.name)
    assert not bad, f"suffix-convention offenders: {bad}"
