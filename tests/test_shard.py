"""dp-sharded solve over the (dp × it) mesh (ISSUE 8).

The mesh's dp axis does real work two ways: (a) explicit shard_hint
annotations keep the hot [W, T] viability masks, bank [NCAP, T] columns
and kscan [W, T, GR] grid partitioned over (dp × it) instead of
replicated, and (b) the pipelined fill's chunk groups solve
SPECULATIVELY one-per-dp-row in a single batched dispatch
(ops_solver.solve_fill_dp / solve_kscan_dp), merged exact-or-replay: a
group grafts without re-solving only when every live committed claim is
provably capacity-dead for it (the frozen-bank eviction rule; for kscan
kinds a per-domain predicate over the [W, T, GR] grid plus topology
record/apply disjointness — ISSUE 13), else it replays sequentially.
The commit decision itself is computed ON DEVICE and fetched as one
packed verdict word per merge round. Either way the result must be
BIT-identical to the single-device solve and the host oracle — these
tests pin that, plus the fetch_tree regression the sharded outputs
exposed.

Everything here runs in-process on the 8-virtual-device CPU mesh the
whole suite already forces (tests/conftest.py); the subprocess twin with
a fresh backend + KTPU_MESH override lives in tests/test_mesh_parity.py.
"""

import numpy as np
import pytest

import bench
from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import TopologySpreadConstraint, make_pod
from karpenter_tpu.parallel import make_mesh

from test_solver import assert_same_packing


def make_templates(n_types=24):
    pool = NodePool()
    pool.metadata.name = "default"
    return build_templates([(pool, instance_types(n_types))])


def mixed_kind_pods(n=256, kinds=8, prefix="m"):
    """Distinct-size kinds: later (smaller) kinds still fit earlier
    kinds' part-full claims, so the dp commit check FAILS and groups
    replay — the adversarial case for the merge."""
    pods = []
    per = n // kinds
    for i in range(n):
        k = i // per
        pods.append(
            make_pod(
                f"{prefix}-{i}",
                cpu=[0.25, 0.5, 1.0][k % 3],
                memory=f"{[0.5, 1.0][k % 2]}Gi",
            )
        )
    return pods


def saturating_kind_pods(n=256, kinds=8, prefix="s"):
    """Identical-size kinds big enough that every claim fills to capacity
    — committed claims go capacity-dead immediately, so speculative
    groups GRAFT without replaying."""
    pods = []
    per = n // kinds
    for i in range(n):
        p = make_pod(f"{prefix}-{i}", cpu=2.0, memory="1Gi")
        p.metadata.labels = {"grp": str(i // per)}
        pods.append(p)
    return pods


def zonal_kind_pods(n=192, kinds=4, prefix="z", shared=False, mixed=False):
    """Kscan-shaped pods: every kind carries a zone-spread constraint so
    the solve takes the kscan path. Disjoint selectors (default) keep the
    kinds' topology state independent, so speculative kscan groups can
    commit; `shared=True` makes every kind record into the selector every
    other kind applies — the record/apply conflict bit refuses all but the
    round's first group. `mixed=True` sizes kinds unevenly so committed
    claims stay alive for later kinds (the deadness bit refuses)."""
    pods = []
    per = n // kinds
    for i in range(n):
        k = i // per
        sel = "z" if shared else f"z{k}"
        if mixed:
            p = make_pod(
                f"{prefix}-{i}",
                cpu=[0.25, 0.5, 1.0][k % 3],
                memory=f"{[0.5, 1.0][k % 2]}Gi",
            )
        else:
            p = make_pod(f"{prefix}-{i}", cpu=2.0, memory="1Gi")
        p.metadata.labels = {"grp": str(k), "spread": sel}
        p.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=l.LABEL_TOPOLOGY_ZONE,
                label_selector={"spread": sel},
            )
        ]
        pods.append(p)
    return pods


def existing_factory(n=2, cpu_avail=4.0):
    """Real existing nodes — the ISSUE 14 debit-delta family."""
    from test_solver import make_existing

    return [make_existing(f"exist-{i}", i, cpu_avail=cpu_avail) for i in range(n)]


def hostname_spread_pods(n=192, kinds=4, prefix="hs", mixed=False):
    """Topology-BEARING fill: hostname-spread kinds have hg interaction
    but zero vg interaction, so they stay batchable (the fill route) and
    ride the topo_fill speculation family. Disjoint per-kind selectors
    keep the hg record/apply sets independent so groups can commit."""
    pods = []
    per = n // kinds
    for i in range(n):
        k = i // per
        if mixed:
            p = make_pod(
                f"{prefix}-{i}",
                cpu=[0.25, 0.5, 1.0][k % 3],
                memory=f"{[0.5, 1.0][k % 2]}Gi",
            )
        else:
            p = make_pod(f"{prefix}-{i}", cpu=2.0, memory="1Gi")
        p.metadata.labels = {"grp": str(k), "hspread": f"h{k}"}
        p.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=l.LABEL_HOSTNAME,
                label_selector={"hspread": f"h{k}"},
            )
        ]
        pods.append(p)
    return pods


def perpod_kind_pods(n=256, kinds=4, prefix="pp", shared=False, mixed=False):
    """Per-pod-routed kinds: TWO distinct vg keys per kind (zone +
    capacity-type spread) defeat the single-key kscan check, so the run
    takes the per-pod scan — the solve_perpod_dp family. Disjoint
    selectors (default) let consecutive chunks commit; `shared=True`
    makes every chunk record into the selector every other chunk applies
    (the vg conflict bit refuses); `mixed=True` keeps committed claims
    alive for later chunks (the deadness bit refuses)."""
    pods = []
    per = n // kinds
    for i in range(n):
        k = i // per
        sel = "p" if shared else f"p{k}"
        if mixed:
            p = make_pod(
                f"{prefix}-{i}",
                cpu=[0.25, 0.5, 1.0][k % 3],
                memory=f"{[0.5, 1.0][k % 2]}Gi",
            )
        else:
            p = make_pod(f"{prefix}-{i}", cpu=2.0, memory="1Gi")
        p.metadata.labels = {"grp": str(k), "spread": sel}
        p.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=l.LABEL_TOPOLOGY_ZONE,
                label_selector={"spread": sel},
            ),
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=l.CAPACITY_TYPE_LABEL_KEY,
                label_selector={"spread": sel},
            ),
        ]
        pods.append(p)
    return pods


def dp_scheduler(monkeypatch, *, window=0, chunks=4, enabled=True, n_types=24):
    """A meshed TPUScheduler with the pipeline forced on so the dp path
    engages at test sizes."""
    monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", str(chunks))
    monkeypatch.setenv("KTPU_PIPELINE_MIN_PODS", "32")
    if window:
        monkeypatch.setenv("KTPU_SCAN_WINDOW", str(window))
    else:
        monkeypatch.delenv("KTPU_SCAN_WINDOW", raising=False)
    if not enabled:
        monkeypatch.setenv("KTPU_SHARD_DP", "0")
    else:
        monkeypatch.delenv("KTPU_SHARD_DP", raising=False)
    return TPUScheduler(make_templates(n_types), mesh=make_mesh(8))


def assert_bit_identical(meshed, single):
    assert meshed.assignments == single.assignments
    assert meshed.existing_assignments == single.existing_assignments
    assert len(meshed.claims) == len(single.claims)
    assert [(p.uid, r) for p, r in meshed.unschedulable] == [
        (p.uid, r) for p, r in single.unschedulable
    ]
    for a, b in zip(meshed.claims, single.claims):
        assert a.slot == b.slot
        assert a.hostname == b.hostname
        assert [it.name for it in a.instance_types] == [
            it.name for it in b.instance_types
        ]
        assert a.used == b.used
        assert str(a.requirements) == str(b.requirements)


class TestDpFillParity:
    def test_replay_path_bit_identical(self, monkeypatch):
        """Mixed-size kinds couple chunk groups through tier-2 water
        fills: the commit check must fail and the replay rung must keep
        the solve bit-identical to single-device AND the host oracle."""
        pods = mixed_kind_pods(256)
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(pods)
        shard = sched.last_timings["shard"]
        assert shard["merge_rounds"] >= 1
        assert shard["groups_replayed"] >= 1
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)
        href, _ = bench.host_solve(make_templates(), pods)
        assert_same_packing(href, meshed)

    def test_graft_path_bit_identical(self, monkeypatch):
        """Saturating kinds leave every committed claim capacity-dead, so
        speculative groups graft WITHOUT replaying — and stay
        bit-identical (the commit conditions are a proof, not a
        heuristic)."""
        pods = saturating_kind_pods(256)
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(pods)
        shard = sched.last_timings["shard"]
        assert shard["groups_committed"] >= 2, shard
        assert shard["groups_replayed"] == 0, shard
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)
        href, _ = bench.host_solve(make_templates(), pods)
        assert_same_packing(href, meshed)

    def test_windowed_dp_bit_identical(self, monkeypatch):
        """The dp merge under a small active window: graft appends must
        respect window occupancy (overflow falls back to replay + the
        existing spill escalation) and stay bit-identical."""
        pods = mixed_kind_pods(256, prefix="w")
        sched = dp_scheduler(monkeypatch, window=48)
        meshed = sched.solve(pods)
        assert sched.last_timings["shard"]["merge_rounds"] >= 1
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        monkeypatch.setenv("KTPU_SCAN_WINDOW", "48")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)

    def test_windowed_graft_bit_identical(self, monkeypatch):
        pods = saturating_kind_pods(256, prefix="wg")
        sched = dp_scheduler(monkeypatch, window=64)
        meshed = sched.solve(pods)
        assert sched.last_timings["shard"]["groups_committed"] >= 1
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        monkeypatch.setenv("KTPU_SCAN_WINDOW", "64")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)

    def test_topology_problem_speculates_and_stays_identical(
        self, monkeypatch
    ):
        """A topology-bearing problem used to disqualify the speculative
        FILL path wholesale; ISSUE 14 dropped that gate (the verdict's
        hg record-vs-apply bit carries the coupling), so the topology-free
        fill groups speculate even though zonal kinds share the solve —
        still bit-identical."""
        pods = mixed_kind_pods(128, prefix="t")
        for i in range(32):
            p = make_pod(f"tz-{i}", cpu=0.5, memory="0.5Gi")
            p.metadata.labels = {"spread": "z"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=l.LABEL_TOPOLOGY_ZONE,
                    label_selector={"spread": "z"},
                )
            ]
            pods.append(p)
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(pods)
        shard = sched.last_timings["shard"]
        assert shard["merge_rounds"] >= 1, shard
        # the plain kinds carry no hostname-topology, so they keep the
        # plain `fill` family label
        fams = shard["families"]
        assert fams["fill"]["committed"] + fams["fill"]["replayed"] >= 1
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)

    def test_shard_dp_opt_out(self, monkeypatch):
        """KTPU_SHARD_DP=0 keeps the meshed solve on the sequential
        pipeline (zero merge rounds) with identical results."""
        pods = saturating_kind_pods(128, kinds=4, prefix="o")
        sched = dp_scheduler(monkeypatch, enabled=False)
        meshed = sched.solve(pods)
        assert sched.last_timings["shard"]["merge_rounds"] == 0
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)


class TestDpKscanParity:
    """Speculative dp groups over kscan (zonal-spread) kinds: the
    per-domain capacity-grid deadness predicate plus the topology
    record/apply disjointness bit decide commits on device; refusals
    replay sequentially. Every rung must stay bit-identical to the
    single-device solve and the host oracle."""

    @pytest.mark.parametrize("chunks", [1, 2, 4])
    def test_kscan_graft_bit_identical(self, monkeypatch, chunks):
        """Disjoint selectors + saturating sizes: committed claims go
        capacity-dead in every domain and no kind records into a selector
        another kind applies, so kscan groups GRAFT."""
        from karpenter_tpu.utils.metrics import SHARD_MERGE_ROUNDS

        k0 = SHARD_MERGE_ROUNDS.get(outcome="committed", family="kscan")
        pods = zonal_kind_pods(192, kinds=4, prefix=f"kg{chunks}")
        sched = dp_scheduler(monkeypatch, chunks=chunks)
        meshed = sched.solve(pods)
        if chunks > 1:
            shard = sched.last_timings["shard"]
            fam = shard["families"]["kscan"]
            assert fam["committed"] >= 1, shard
            assert fam["replayed"] == 0, shard
            assert shard["verdict_fetches"] == shard["merge_rounds"]
            assert shard["verdict_bytes"] >= 4 * shard["verdict_fetches"]
            assert (
                SHARD_MERGE_ROUNDS.get(outcome="committed", family="kscan")
                - k0
                == fam["committed"]
            )
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)
        href, _ = bench.host_solve(make_templates(), pods)
        assert_same_packing(href, meshed)

    def test_kscan_replay_bit_identical(self, monkeypatch):
        """Mixed sizes keep earlier kinds' claims alive for later kinds —
        the deadness verdict bit refuses and groups REPLAY, still
        bit-identical."""
        pods = zonal_kind_pods(192, kinds=4, prefix="kr", mixed=True)
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(pods)
        shard = sched.last_timings["shard"]
        assert shard["families"]["kscan"]["replayed"] >= 1, shard
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)
        href, _ = bench.host_solve(make_templates(), pods)
        assert_same_packing(href, meshed)

    def test_kscan_shared_selector_conflict_replays(self, monkeypatch):
        """Every kind recording into the one selector every other kind
        applies: the record/apply conflict bit refuses all but each
        round's first group — commits AND replays, bit-identical."""
        pods = zonal_kind_pods(192, kinds=4, prefix="ks", shared=True)
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(pods)
        fam = sched.last_timings["shard"]["families"]["kscan"]
        assert fam["replayed"] >= 1, fam
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)

    def test_kscan_windowed_bit_identical(self, monkeypatch):
        """Kscan dp merge under a small active window — graft appends
        respect window occupancy exactly as the fill family does."""
        pods = zonal_kind_pods(192, kinds=4, prefix="kw")
        sched = dp_scheduler(monkeypatch, window=48)
        meshed = sched.solve(pods)
        assert sched.last_timings["shard"]["merge_rounds"] >= 1
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        monkeypatch.setenv("KTPU_SCAN_WINDOW", "48")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)

    def test_kscan_opt_out(self, monkeypatch):
        """KTPU_SHARD_KSCAN=0 keeps kscan runs sequential (fill
        speculation untouched) with identical results."""
        pods = zonal_kind_pods(192, kinds=4, prefix="ko")
        monkeypatch.setenv("KTPU_SHARD_KSCAN", "0")
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(pods)
        fam = sched.last_timings["shard"]["families"]["kscan"]
        assert fam["committed"] == 0 and fam["replayed"] == 0
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)


class TestDpExistingParity:
    """Speculative dp groups over solves WITH real existing nodes
    (ISSUE 14a): every row carries per-existing-node capacity-debit
    deltas and the verdict's disjoint-touch bit decides commits on
    device. Rows that both debit the same existing node refuse; rows
    touching disjoint node sets (or none) graft order-free through
    merge_shard_fill — always bit-identical to the single-device solve
    carrying the same existing nodes."""

    @pytest.mark.parametrize(
        "chunks",
        [
            pytest.param(1, marks=pytest.mark.slow),
            pytest.param(2, marks=pytest.mark.slow),
            4,
        ],
    )
    def test_existing_commit_bit_identical(self, monkeypatch, chunks):
        pods = saturating_kind_pods(256, prefix=f"ex{chunks}")
        sched = dp_scheduler(monkeypatch, chunks=chunks)
        meshed = sched.solve(pods, existing_factory())
        if chunks > 1:
            shard = sched.last_timings["shard"]
            fam = shard["families"]["existing"]
            # early rows racing for the same existing node replay; once
            # the nodes saturate the debit bit proves disjointness and
            # groups commit
            assert fam["committed"] >= 1, shard
            assert shard["coverage"]["existing"]["dp"] == (
                fam["committed"] + fam["replayed"]
            )
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods, existing_factory())
        assert_bit_identical(meshed, single)

    @pytest.mark.slow
    def test_existing_contention_replays_bit_identical(self, monkeypatch):
        """Small pods that all fit the existing nodes: every row debits
        the same nodes, the disjoint-touch bit refuses, groups replay —
        still bit-identical (including existing_assignments)."""
        pods = mixed_kind_pods(256, prefix="exr")
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(pods, existing_factory(cpu_avail=8.0))
        fam = sched.last_timings["shard"]["families"]["existing"]
        assert fam["replayed"] >= 1, fam
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(
            pods, existing_factory(cpu_avail=8.0)
        )
        assert_bit_identical(meshed, single)
        assert meshed.existing_assignments == single.existing_assignments

    @pytest.mark.slow
    def test_existing_windowed_bit_identical(self, monkeypatch):
        pods = saturating_kind_pods(256, prefix="exw")
        sched = dp_scheduler(monkeypatch, window=48)
        meshed = sched.solve(pods, existing_factory())
        assert sched.last_timings["shard"]["merge_rounds"] >= 1
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        monkeypatch.setenv("KTPU_SCAN_WINDOW", "48")
        single = TPUScheduler(make_templates()).solve(pods, existing_factory())
        assert_bit_identical(meshed, single)

    def test_existing_opt_out(self, monkeypatch):
        """KTPU_SHARD_EXISTING=0 re-imposes the old `no real existing
        nodes` gate: zero merge rounds, coverage records the sequential
        routing, results identical."""
        monkeypatch.setenv("KTPU_SHARD_EXISTING", "0")
        pods = saturating_kind_pods(128, kinds=4, prefix="exo")
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(pods, existing_factory())
        shard = sched.last_timings["shard"]
        assert shard["merge_rounds"] == 0, shard
        assert shard["coverage"]["existing"]["sequential"] >= 1, shard
        assert shard["coverage"]["existing"]["dp"] == 0, shard
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods, existing_factory())
        assert_bit_identical(meshed, single)


class TestDpTopoFillParity:
    """Speculative dp groups over topology-BEARING fill (ISSUE 14b):
    hostname-spread / anti-affinity kinds stay on the fill route and the
    verdict's hg record-vs-apply disjointness bit (the mechanism
    solve_kscan_dp already used for vg) decides commits on device."""

    @pytest.mark.parametrize(
        "chunks",
        [
            pytest.param(1, marks=pytest.mark.slow),
            pytest.param(2, marks=pytest.mark.slow),
            4,
        ],
    )
    def test_hostname_spread_commit_bit_identical(self, monkeypatch, chunks):
        pods = hostname_spread_pods(192, kinds=4, prefix=f"ts{chunks}")
        sched = dp_scheduler(monkeypatch, chunks=chunks)
        meshed = sched.solve(pods)
        if chunks > 1:
            shard = sched.last_timings["shard"]
            fam = shard["families"]["topo_fill"]
            assert fam["committed"] >= 1, shard
            assert fam["replayed"] == 0, shard
            assert shard["coverage"]["topo_fill"]["dp"] == (
                fam["committed"] + fam["replayed"]
            )
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)
        href, _ = bench.host_solve(make_templates(), pods)
        assert_same_packing(href, meshed)

    @pytest.mark.slow
    def test_shared_hg_selector_conflict_replays(self, monkeypatch):
        """Self-anti-affinity kinds sharing chunk groups: rows recording
        into hostname groups other rows apply refuse on the hg bit and
        replay — commits AND replays, bit-identical."""
        from karpenter_tpu.models.pod import PodAffinityTerm

        pods = []
        for i in range(96):
            k = i // 24
            p = make_pod(f"ta-{i}", cpu=0.5, memory="0.5Gi")
            p.metadata.labels = {"app": f"db{k}"}
            p.spec.pod_anti_affinity = [
                PodAffinityTerm(
                    topology_key=l.LABEL_HOSTNAME,
                    label_selector={"app": f"db{k}"},
                )
            ]
            pods.append(p)
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(pods)
        fam = sched.last_timings["shard"]["families"]["topo_fill"]
        assert fam["replayed"] >= 1, fam
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)

    @pytest.mark.slow
    def test_topo_fill_windowed_bit_identical(self, monkeypatch):
        pods = hostname_spread_pods(192, kinds=4, prefix="tw")
        sched = dp_scheduler(monkeypatch, window=48)
        meshed = sched.solve(pods)
        assert sched.last_timings["shard"]["merge_rounds"] >= 1
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        monkeypatch.setenv("KTPU_SCAN_WINDOW", "48")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)

    def test_topo_fill_opt_out(self, monkeypatch):
        """KTPU_SHARD_DP=0 keeps topology-bearing fill sequential with
        identical results (the family's opt-out is the dp master knob)."""
        pods = hostname_spread_pods(128, kinds=4, prefix="to")
        sched = dp_scheduler(monkeypatch, enabled=False)
        meshed = sched.solve(pods)
        shard = sched.last_timings["shard"]
        assert shard["merge_rounds"] == 0
        assert shard["coverage"]["topo_fill"]["sequential"] >= 1, shard
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)


class TestDpPerpodParity:
    """Speculative dp rows over consecutive per-pod chunks (ISSUE 14c):
    solve_perpod_dp vmaps the per-pod scan one chunk per dp row and
    merge_shard_kscan grafts committed rows (window fields + vg/hg
    deltas + existing-node debits). The chunk count is
    ceil(pods / KTPU_SOLVE_CHUNK), so the parametrized chunk sizes below
    give {1, 2, 4} chunks over 256 pods."""

    @pytest.mark.parametrize(
        "solve_chunk",
        [
            pytest.param(256, marks=pytest.mark.slow),
            pytest.param(128, marks=pytest.mark.slow),
            64,
        ],
    )
    def test_perpod_commit_bit_identical(self, monkeypatch, solve_chunk):
        monkeypatch.setenv("KTPU_SOLVE_CHUNK", str(solve_chunk))
        n_chunks = 256 // solve_chunk
        pods = perpod_kind_pods(256, prefix=f"pp{n_chunks}")
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(pods)
        shard = sched.last_timings["shard"]
        fam = shard["families"]["perpod"]
        if n_chunks > 1:
            assert fam["committed"] >= 1, shard
            assert fam["replayed"] == 0, shard
            assert shard["coverage"]["perpod"]["dp"] == (
                fam["committed"] + fam["replayed"]
            )
        else:
            # a single chunk has nothing to speculate against
            assert fam["committed"] + fam["replayed"] == 0, shard
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)
        href, _ = bench.host_solve(make_templates(), pods)
        assert_same_packing(href, meshed)

    @pytest.mark.slow
    def test_perpod_shared_selector_conflict_replays(self, monkeypatch):
        """One shared spread selector across every chunk: each chunk
        records into the vg groups every other chunk applies — the
        conflict bit refuses all but each round's first row."""
        monkeypatch.setenv("KTPU_SOLVE_CHUNK", "64")
        pods = perpod_kind_pods(256, prefix="pps", shared=True)
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(pods)
        fam = sched.last_timings["shard"]["families"]["perpod"]
        assert fam["replayed"] >= 1, fam
        assert fam["committed"] >= 1, fam
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)

    @pytest.mark.slow
    def test_perpod_mixed_sizes_replay_bit_identical(self, monkeypatch):
        monkeypatch.setenv("KTPU_SOLVE_CHUNK", "64")
        pods = perpod_kind_pods(256, prefix="ppm", mixed=True)
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(pods)
        fam = sched.last_timings["shard"]["families"]["perpod"]
        assert fam["replayed"] >= 1, fam
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)

    @pytest.mark.slow
    def test_perpod_windowed_bit_identical(self, monkeypatch):
        monkeypatch.setenv("KTPU_SOLVE_CHUNK", "64")
        pods = perpod_kind_pods(256, prefix="ppw")
        sched = dp_scheduler(monkeypatch, window=48)
        meshed = sched.solve(pods)
        assert sched.last_timings["shard"]["merge_rounds"] >= 1
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        monkeypatch.setenv("KTPU_SCAN_WINDOW", "48")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)

    def test_perpod_opt_out(self, monkeypatch):
        """KTPU_SHARD_PERPOD=0 opts per-pod runs (only) back onto the
        sequential scan — zero perpod dp rounds, coverage records the
        sequential routing, identical results."""
        monkeypatch.setenv("KTPU_SOLVE_CHUNK", "64")
        monkeypatch.setenv("KTPU_SHARD_PERPOD", "0")
        pods = perpod_kind_pods(256, prefix="ppo")
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(pods)
        shard = sched.last_timings["shard"]
        fam = shard["families"]["perpod"]
        assert fam["committed"] + fam["replayed"] == 0, shard
        assert shard["coverage"]["perpod"]["sequential"] >= 1, shard
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)


def mv_templates(n_types=24, mv=2):
    """Templates whose pool carries an instance-type minValues floor —
    the enforced-minValues constraint class rung 1 admits to perpod-dp."""
    from test_solver import default_pool

    pool = default_pool(
        "default",
        requirements=[
            {"key": l.LABEL_INSTANCE_TYPE, "operator": "Exists", "minValues": mv}
        ],
    )
    return build_templates([(pool, instance_types(n_types))])


def host_oracle(templates, pods, budgets=None):
    """The host oracle with budgets (bench.host_solve has no budgets
    parameter), on the same internally-built topology."""
    from karpenter_tpu.controllers.provisioning.host_scheduler import (
        HostScheduler,
    )
    from karpenter_tpu.controllers.provisioning.topology import (
        Topology,
        build_universe_domains,
    )

    topo = Topology.build(
        list(pods), build_universe_domains(templates, []), []
    )
    return HostScheduler(templates, budgets=budgets, topology=topo).solve(
        list(pods)
    )


class TestDpBudgetParity:
    """Rung 1 (ISSUE 20): enforced minValues + finite disruption budgets
    no longer disqualify perpod-dp. Budget/nodes_budget debits and
    reservation capacities ride the speculative ShardKscanState slice as
    order-free deltas; a budget/reservation disjointness verdict bit
    refuses any row whose debit an earlier row's template application
    could observe. Chunks {1, 2, 4} over 256 pods, each vs the
    single-device sequential solve AND the host oracle."""

    @pytest.mark.parametrize(
        "solve_chunk",
        [
            pytest.param(256, marks=pytest.mark.slow),
            pytest.param(128, marks=pytest.mark.slow),
            64,
        ],
    )
    def test_perpod_mv_budget_bit_identical(self, monkeypatch, solve_chunk):
        monkeypatch.setenv("KTPU_SOLVE_CHUNK", str(solve_chunk))
        n_chunks = 256 // solve_chunk
        budgets = {"default": {"cpu": 1e6}}
        pods = perpod_kind_pods(256, prefix=f"bp{n_chunks}")
        templates = mv_templates()
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "4")
        monkeypatch.setenv("KTPU_PIPELINE_MIN_PODS", "32")
        monkeypatch.delenv("KTPU_SCAN_WINDOW", raising=False)
        monkeypatch.delenv("KTPU_SHARD_DP", raising=False)
        sched = TPUScheduler(templates, mesh=make_mesh(8))
        meshed = sched.solve(pods, budgets={"default": dict(budgets["default"])})
        shard = sched.last_timings["shard"]
        fam = shard["families"]["perpod"]
        if n_chunks > 1:
            # the round's FIRST row always commits (no earlier row to
            # conflict with); later rows that applied the debited
            # template refuse on the budget bit and replay — both
            # outcomes ride the dp path
            assert fam["committed"] >= 1, shard
        else:
            assert fam["committed"] + fam["replayed"] == 0, shard
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(mv_templates()).solve(
            pods, budgets={"default": dict(budgets["default"])}
        )
        assert_bit_identical(meshed, single)
        href = host_oracle(
            mv_templates(), pods, budgets={"default": dict(budgets["default"])}
        )
        assert_same_packing(href, meshed)

    def test_perpod_tight_budget_replays_bit_identical(self, monkeypatch):
        """A budget tight enough that the candidate set narrows as debits
        land: later chunks' rows must refuse on the budget bit (their
        speculative base lied about the remaining budget) and replay —
        still bit-identical both ways."""
        monkeypatch.setenv("KTPU_SOLVE_CHUNK", "64")
        budgets = {"default": {"nodes": 6.0}}
        pods = perpod_kind_pods(256, prefix="bt")
        templates = make_templates()
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "4")
        monkeypatch.setenv("KTPU_PIPELINE_MIN_PODS", "32")
        monkeypatch.delenv("KTPU_SCAN_WINDOW", raising=False)
        monkeypatch.delenv("KTPU_SHARD_DP", raising=False)
        sched = TPUScheduler(templates, mesh=make_mesh(8))
        meshed = sched.solve(pods, budgets={"default": dict(budgets["default"])})
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(
            pods, budgets={"default": dict(budgets["default"])}
        )
        assert_bit_identical(meshed, single)
        href = host_oracle(
            make_templates(), pods, budgets={"default": dict(budgets["default"])}
        )
        assert_same_packing(href, meshed)


class TestDpGangKscanParity:
    """Rung 2 (ISSUE 20): a gang carrying zonal-spread topology rides the
    gang-aware kscan on device (one vg evaluation per rank block inside
    the gang kernel) while zonal singles in the same solve keep dp-
    speculating — no _GangHostRoute, zero gang_constraints fallbacks.
    Chunks {1, 2, 4} vs single-device AND host oracle."""

    @pytest.mark.parametrize(
        "chunks",
        [
            pytest.param(1, marks=pytest.mark.slow),
            pytest.param(2, marks=pytest.mark.slow),
            4,
        ],
    )
    def test_gang_zonal_with_kscan_singles_bit_identical(
        self, monkeypatch, chunks
    ):
        from karpenter_tpu.gang import make_gang_pods
        from karpenter_tpu.utils import metrics

        before = metrics.SOLVER_FALLBACK.get(reason="gang_constraints")
        gang = make_gang_pods("dgz", 6, cpu=1.0)
        for p in gang:
            p.metadata.labels = dict(p.metadata.labels or {}, spread="dgz")
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=l.LABEL_TOPOLOGY_ZONE,
                    label_selector={"spread": "dgz"},
                )
            ]
        pods = gang + zonal_kind_pods(192, prefix=f"dgz{chunks}")
        sched = dp_scheduler(monkeypatch, chunks=chunks)
        meshed = sched.solve(pods)
        assert (
            metrics.SOLVER_FALLBACK.get(reason="gang_constraints") == before
        ), "gang+zonal must stay on device"
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(pods)
        assert_bit_identical(meshed, single)
        href, _ = bench.host_solve(make_templates(), pods)
        assert_same_packing(href, meshed)

    def test_gang_budget_meshed_bit_identical(self, monkeypatch):
        """Gang × finite budgets on the meshed scheduler: the per-block
        debit (subtractMax over the block-narrowed remaining set) matches
        the host's _charge_budget exactly."""
        from karpenter_tpu.gang import make_gang_pods
        from karpenter_tpu.utils import metrics

        before = metrics.SOLVER_FALLBACK.get(reason="gang_constraints")
        budgets = {"default": {"cpu": 64.0}}
        pods = make_gang_pods("dgb", 4, cpu=1.0) + saturating_kind_pods(
            128, kinds=4, prefix="dgb"
        )
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(pods, budgets={"default": dict(budgets["default"])})
        assert (
            metrics.SOLVER_FALLBACK.get(reason="gang_constraints") == before
        ), "gang+budgets must stay on device"
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(
            pods, budgets={"default": dict(budgets["default"])}
        )
        assert_bit_identical(meshed, single)
        href = host_oracle(
            make_templates(), pods, budgets={"default": dict(budgets["default"])}
        )
        assert_same_packing(href, meshed)


class TestNewFamilyQuarantine:
    """KTPU_GUARD_LIE=speculative against each ISSUE 14 family: the
    shadow audit catches the corrupted graft, quarantines the
    speculative path, and the NEXT meshed solve routes that family back
    to the sequential scan (coverage proves it) — exact either way."""

    @pytest.fixture(autouse=True)
    def _clean_guard_state(self, monkeypatch):
        from karpenter_tpu import guard

        for var in ("KTPU_GUARD_AUDIT_RATE", "KTPU_GUARD_LIE"):
            monkeypatch.delenv(var, raising=False)
        guard.QUARANTINE.reset()
        guard.reset_log()
        yield
        guard.QUARANTINE.reset()
        guard.reset_log()

    def _lie_and_recover(
        self, monkeypatch, family, pods, existing=None, budgets=None
    ):
        from karpenter_tpu import guard

        def kw():
            return dict(budgets={k: dict(v) for k, v in budgets.items()}) if budgets else {}

        monkeypatch.setenv("KTPU_GUARD_AUDIT_RATE", "1.0")
        monkeypatch.setenv("KTPU_GUARD_LIE", "speculative")
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(list(pods), list(existing or []), **kw())
        assert guard.divergences("speculative")
        assert guard.QUARANTINE.active("speculative")
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(make_templates()).solve(
            list(pods), list(existing or []), **kw()
        )
        assert_bit_identical(meshed, single)
        # quarantined: the family rides the sequential scan, still exact
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "4")
        monkeypatch.delenv("KTPU_GUARD_LIE", raising=False)
        sched2 = dp_scheduler(monkeypatch)
        r2 = sched2.solve(list(pods), list(existing or []), **kw())
        assert_bit_identical(meshed, r2)
        shard = sched2.last_timings["shard"]
        assert shard["merge_rounds"] == 0, shard
        fam = shard["families"][family]
        assert fam["committed"] + fam["replayed"] == 0, shard
        assert shard["coverage"][family]["sequential"] >= 1, shard

    @pytest.mark.slow
    def test_lying_existing_family_quarantines(self, monkeypatch):
        self._lie_and_recover(
            monkeypatch,
            "existing",
            saturating_kind_pods(128, kinds=4, prefix="qe"),
            existing=existing_factory(),
        )

    @pytest.mark.slow
    def test_lying_topo_fill_family_quarantines(self, monkeypatch):
        self._lie_and_recover(
            monkeypatch,
            "topo_fill",
            hostname_spread_pods(128, kinds=4, prefix="qt"),
        )

    @pytest.mark.slow
    def test_lying_perpod_family_quarantines(self, monkeypatch):
        monkeypatch.setenv("KTPU_SOLVE_CHUNK", "64")
        self._lie_and_recover(
            monkeypatch, "perpod", perpod_kind_pods(128, kinds=4, prefix="qp")
        )

    def test_lying_perpod_budget_family_quarantines(self, monkeypatch):
        """Rung 1 under the lie: the perpod family speculating under
        finite budgets quarantines back to its sequential twin exactly
        like the budget-free class."""
        monkeypatch.setenv("KTPU_SOLVE_CHUNK", "64")
        self._lie_and_recover(
            monkeypatch,
            "perpod",
            perpod_kind_pods(128, kinds=4, prefix="qb"),
            budgets={"default": {"cpu": 1e6}},
        )

    def test_lying_gang_path_quarantines_to_host(self, monkeypatch):
        """Rung 2 under the lie: KTPU_GUARD_LIE=gang corrupts the device
        gang solve; the solve-level shadow audit (host oracle twin)
        catches it, returns the oracle result, and quarantines the "gang"
        path — the NEXT constraint-bearing gang solve routes through
        _GangHostRoute to the host oracle, still exact."""
        from karpenter_tpu import guard
        from karpenter_tpu.gang import make_gang_pods
        from karpenter_tpu.utils import metrics

        gang = make_gang_pods("qg", 4, cpu=1.0)
        for p in gang:
            p.metadata.labels = dict(p.metadata.labels or {}, spread="qg")
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=l.LABEL_TOPOLOGY_ZONE,
                    label_selector={"spread": "qg"},
                )
            ]
        pods = gang + [make_pod(f"qgs-{i}", cpu=0.5) for i in range(6)]
        href = bench.host_solve(make_templates(), pods)[0]
        monkeypatch.setenv("KTPU_GUARD_LIE", "gang")
        sched = TPUScheduler(make_templates())
        result = sched.solve(list(pods))
        assert guard.divergences("gang")
        assert guard.QUARANTINE.active("gang")
        # the audit returned the host twin's (exact) result
        assert_same_packing(href, result)
        # quarantined: the next solve routes via _GangHostRoute to the
        # host oracle — the fallback counter proves it, parity holds
        monkeypatch.delenv("KTPU_GUARD_LIE", raising=False)
        before = metrics.SOLVER_FALLBACK.get(reason="gang_constraints")
        sched2 = TPUScheduler(make_templates())
        r2 = sched2.solve(list(pods))
        assert (
            metrics.SOLVER_FALLBACK.get(reason="gang_constraints")
            == before + 1
        )
        assert_same_packing(href, r2)


class TestVerdictDecode:
    """Packed commit-verdict word wire-format regression: pack_bool_np is
    the layout oracle; leading_ones is the host decode the merge loop
    trusts for 'how many groups commit'."""

    @pytest.mark.parametrize("n", [1, 2, 4, 8, 37])
    def test_leading_ones_patterns(self, n):
        from karpenter_tpu.ops.kernels import leading_ones, pack_bool_np

        assert leading_ones(pack_bool_np(np.ones(n, bool)), n) == n
        assert leading_ones(pack_bool_np(np.zeros(n, bool)), n) == 0
        for k in range(n + 1):
            bits = np.zeros(n, bool)
            bits[:k] = True
            assert leading_ones(pack_bool_np(bits), n) == k
        if n >= 3:
            # a well-formed word is prefix-ANDed on device, but the
            # decode must not rely on that: set bits after the first
            # clear one are ignored
            bits = np.ones(n, bool)
            bits[1] = False
            assert leading_ones(pack_bool_np(bits), n) == 1

    def test_device_host_pack_parity(self):
        import jax.numpy as jnp

        from karpenter_tpu.ops.kernels import pack_bool, pack_bool_np

        rng = np.random.default_rng(7)
        for n in (1, 8, 33, 64):
            bits = rng.random(n) > 0.5
            np.testing.assert_array_equal(
                np.asarray(pack_bool(jnp.asarray(bits))), pack_bool_np(bits)
            )


class TestShardObservability:
    def test_last_timings_shard_record(self, monkeypatch):
        """Every meshed solve records the mesh extents, merge/commit
        counters, per-group pod counts and the replicated-bytes estimate;
        un-meshed solves record nothing."""
        pods = saturating_kind_pods(128, kinds=4, prefix="obs")
        sched = dp_scheduler(monkeypatch)
        sched.solve(pods)
        shard = sched.last_timings["shard"]
        assert shard["dp"] == 2 and shard["it"] == 4
        assert shard["merge_rounds"] >= 1
        assert shard["groups_committed"] + shard["groups_replayed"] == len(
            shard["group_pods"]
        )
        assert sum(shard["group_pods"]) == len(pods)
        assert shard["replicated_bytes"] > 0
        # ONE verdict fetch per merge round — the round's single host
        # synchronization (ISSUE 13 tentpole)
        assert shard["verdict_fetches"] == shard["merge_rounds"]
        assert shard["verdict_bytes"] >= 4 * shard["verdict_fetches"]
        assert shard["sync_blocked_s"] >= 0.0
        assert shard["merge_wall_s"] >= shard["sync_blocked_s"]
        fams = shard["families"]
        assert sum(
            fams[f]["committed"] + fams[f]["replayed"] for f in fams
        ) == shard["groups_committed"] + shard["groups_replayed"]
        # the coverage ledger's dp column IS the speculation ledger:
        # every group that entered a merge round (committed or replayed)
        # was counted eligible-for-dp exactly once
        for f, fam in fams.items():
            assert shard["coverage"][f]["dp"] == (
                fam["committed"] + fam["replayed"]
            ), (f, shard)
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        plain = TPUScheduler(make_templates())
        plain.solve(pods)
        assert "shard" not in plain.last_timings

    def test_merge_round_metrics(self, monkeypatch):
        from karpenter_tpu.utils.metrics import (
            SHARD_MERGE_ROUNDS,
            SHARD_REPLICATED_BYTES,
            SHARD_VERDICT_BYTES,
        )

        def totals(outcome):
            return sum(
                SHARD_MERGE_ROUNDS.get(outcome=outcome, family=f)
                for f in ("fill", "existing", "topo_fill", "kscan", "perpod")
            )

        c0, r0 = totals("committed"), totals("replayed")
        v0 = SHARD_VERDICT_BYTES.get()
        sched = dp_scheduler(monkeypatch)
        sched.solve(saturating_kind_pods(128, kinds=4, prefix="met"))
        shard = sched.last_timings["shard"]
        assert totals("committed") - c0 == shard["groups_committed"]
        assert totals("replayed") - r0 == shard["groups_replayed"]
        assert SHARD_VERDICT_BYTES.get() - v0 == shard["verdict_bytes"]
        assert SHARD_REPLICATED_BYTES.get() == shard["replicated_bytes"]


class TestFetchTreeSharded:
    def test_wire_pack_of_partitioned_arrays(self):
        """Regression: the jitted wire packer miscompiles under GSPMD
        when any input is partitioned (ints came back scaled by the shard
        count, bools bit-shifted). fetch_tree must canonicalize to
        replicated before packing."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from karpenter_tpu.ops.kernels import fetch_tree

        mesh = make_mesh(8)
        bools = np.random.default_rng(0).random((512, 24)) > 0.5
        ints = np.arange(512, dtype=np.int32)

        @jax.jit
        def f(b, i):
            b = jax.lax.with_sharding_constraint(
                b, NamedSharding(mesh, P("dp", "it"))
            )
            return b, i * 1

        with mesh:
            b_s, i_s = f(jnp.asarray(bools), jnp.asarray(ints))
        got_b, got_i = fetch_tree([b_s, i_s])
        np.testing.assert_array_equal(np.asarray(got_b), bools)
        np.testing.assert_array_equal(np.asarray(got_i), ints)

    def test_uneven_shard_axes(self):
        """Uneven (non-divisible) shard extents must round-trip too."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from karpenter_tpu.ops.kernels import fetch_tree

        mesh = make_mesh(8)
        vals = np.arange(77 * 13, dtype=np.int32).reshape(77, 13)

        @jax.jit
        def f(x):
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P("dp", "it"))
            )

        with mesh:
            x = f(jnp.asarray(vals))
        (got,) = fetch_tree([x])
        np.testing.assert_array_equal(np.asarray(got), vals)
