"""DRA scheduler/controller integration tests.

Behavioral ports of the reference's DRA scheduling wiring
(scheduling/scheduler.go resolvePodClaims, nodeclaim.go:179-283 CanAdd/Add,
existingnode.go:81, the deviceallocation controller, and the
dra-kwok-driver harness): instance-type pruning by allocation survival,
claim status writes at launch collapse, node-local slice publication,
claim sharing pinning pods to the allocated node, and device contention
producing unschedulable pods.
"""

import pytest

from karpenter_tpu.cloudprovider.fake import new_instance_type
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.scheduling.dra import (
    Device,
    DeviceClass,
    DeviceRequest,
    ResourceClaim,
    ResourceSlice,
)
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.options import Options


def tpu_slice_template():
    """A 4-device accelerator template, as a cloud provider would declare
    for an accelerator instance type."""
    return ResourceSlice(
        driver="tpu.dra.x-k8s.io",
        pool="accel",
        potential=True,
        devices=[Device(name=f"chip{i}", attributes={"kind": "tpu"}) for i in range(4)],
    )


def dra_catalog():
    small = new_instance_type("small-4x", cpu=4)
    accel = new_instance_type("accel-8x", cpu=8)
    accel.dra_slices = [tpu_slice_template()]
    return [small, accel]


def dra_options():
    opts = Options()
    opts.feature_gates.dynamic_resources = True
    return opts


def make_harness(catalog=None, options=None):
    clock = FakeClock()
    store = ObjectStore(clock)
    cloud = KwokCloudProvider(store, catalog=catalog if catalog is not None else dra_catalog())
    mgr = Manager(store, cloud, clock, options=options or dra_options())
    store.create(ObjectStore.NODEPOOLS, NodePool())
    store.create(
        ObjectStore.DEVICE_CLASSES,
        DeviceClass(name="tpu", selectors=['device.attributes["kind"] == "tpu"']),
    )
    return clock, store, cloud, mgr


def settle(mgr, cloud, store):
    mgr.run_until_idle()
    cloud.simulate_kubelet_ready()
    mgr.run_until_idle()
    KubeSchedulerSim(store, mgr.cluster).bind_pending()
    mgr.run_until_idle()


class TestDRAProvisioning:
    def test_template_claim_end_to_end(self):
        clock, store, cloud, mgr = make_harness()
        store.create(
            ObjectStore.RESOURCE_CLAIMS,
            ResourceClaim(name="train", requests=[DeviceRequest(name="r0", device_class="tpu", count=2)]),
        )
        pod = make_pod("worker", cpu=1.0, resource_claims=["train"])
        store.create(ObjectStore.PODS, pod)
        settle(mgr, cloud, store)

        # The pod landed on a node of the accelerator type.
        pod = store.get(ObjectStore.PODS, "worker")
        assert pod.spec.node_name
        node = store.get(ObjectStore.NODES, pod.spec.node_name)
        assert node.metadata.labels[l.LABEL_INSTANCE_TYPE] == "accel-8x"

        # The claim collapsed: status allocation written, node-pinned.
        rc = store.get(ObjectStore.RESOURCE_CLAIMS, "train")
        assert rc.allocation is not None
        assert len(rc.allocation.devices) == 2
        assert rc.allocation.devices[0].driver == "tpu.dra.x-k8s.io"
        hostname_req = rc.allocation.node_selector_terms[0].get(l.LABEL_HOSTNAME)
        assert hostname_req.has(pod.spec.node_name)
        assert rc.reserved_for == [pod.uid]

        # The driver published the node-local slice (node-scoped pool).
        slices = store.list(ObjectStore.RESOURCE_SLICES)
        assert len(slices) == 1
        assert slices[0].node_name == pod.spec.node_name
        assert slices[0].pool == f"accel-{pod.spec.node_name}"
        assert rc.allocation.devices[0].pool == slices[0].pool

    def test_allocation_prunes_instance_types(self):
        clock, store, cloud, mgr = make_harness()
        store.create(
            ObjectStore.RESOURCE_CLAIMS,
            ResourceClaim(name="c", requests=[DeviceRequest(name="r0", device_class="tpu")]),
        )
        store.create(ObjectStore.PODS, make_pod("p", cpu=1.0, resource_claims=["c"]))
        mgr.run_until_idle()
        claims = store.nodeclaims()
        assert len(claims) == 1
        it_req = next(
            r for r in claims[0].spec.requirements if r["key"] == l.LABEL_INSTANCE_TYPE
        )
        # small-4x survived resource filtering but not device allocation.
        assert it_req["values"] == ["accel-8x"]

    def test_missing_claim_blocks_pod(self):
        clock, store, cloud, mgr = make_harness()
        store.create(ObjectStore.PODS, make_pod("p", cpu=1.0, resource_claims=["nope"]))
        mgr.run_until_idle()
        assert store.nodeclaims() == []

    def test_gate_off_ignores_claims(self):
        opts = Options()  # DynamicResources defaults off, like the reference
        clock, store, cloud, mgr = make_harness(options=opts)
        store.create(
            ObjectStore.RESOURCE_CLAIMS,
            ResourceClaim(name="c", requests=[DeviceRequest(name="r0", device_class="tpu")]),
        )
        store.create(ObjectStore.PODS, make_pod("p", cpu=1.0, resource_claims=["c"]))
        mgr.run_until_idle()
        claims = store.nodeclaims()
        assert len(claims) == 1
        it_req = next(
            r for r in claims[0].spec.requirements if r["key"] == l.LABEL_INSTANCE_TYPE
        )
        # claims ignored: the cheaper non-accelerator type wins
        assert "small-4x" in it_req["values"]

    def test_shared_claim_pins_second_pod_to_same_node(self):
        clock, store, cloud, mgr = make_harness()
        store.create(
            ObjectStore.RESOURCE_CLAIMS,
            ResourceClaim(name="shared", requests=[DeviceRequest(name="r0", device_class="tpu")]),
        )
        store.create(ObjectStore.PODS, make_pod("p1", cpu=1.0, resource_claims=["shared"]))
        settle(mgr, cloud, store)
        p1 = store.get(ObjectStore.PODS, "p1")
        assert p1.spec.node_name

        store.create(ObjectStore.PODS, make_pod("p2", cpu=1.0, resource_claims=["shared"]))
        settle(mgr, cloud, store)
        p2 = store.get(ObjectStore.PODS, "p2")
        assert p2.spec.node_name == p1.spec.node_name
        assert len(store.nodes()) == 1
        rc = store.get(ObjectStore.RESOURCE_CLAIMS, "shared")
        assert p1.uid in rc.reserved_for and p2.uid in rc.reserved_for

    def test_in_cluster_device_contention(self):
        clock, store, cloud, mgr = make_harness()
        # One published single-device pool reachable from any node.
        store.create(
            ObjectStore.RESOURCE_SLICES,
            ResourceSlice(
                driver="fpga.dra.x-k8s.io",
                pool="shared-pool",
                all_nodes=True,
                devices=[Device(name="only", attributes={"kind": "fpga"})],
            ),
        )
        store.create(
            ObjectStore.DEVICE_CLASSES,
            DeviceClass(name="fpga", selectors=['device.attributes["kind"] == "fpga"']),
        )
        for i in (1, 2):
            store.create(
                ObjectStore.RESOURCE_CLAIMS,
                ResourceClaim(name=f"c{i}", requests=[DeviceRequest(name="r0", device_class="fpga")]),
            )
            store.create(ObjectStore.PODS, make_pod(f"p{i}", cpu=1.0, resource_claims=[f"c{i}"]))
        settle(mgr, cloud, store)
        bound = [p for p in store.pods() if p.spec.node_name]
        assert len(bound) == 1
        # The winning claim holds the device in its committed status.
        winner = bound[0].spec.resource_claims[0]
        rc = store.get(ObjectStore.RESOURCE_CLAIMS, winner)
        assert rc.allocation is not None
        assert rc.allocation.devices[0].device == "only"

    def test_two_pods_two_claims_share_template_node(self):
        # Two pods with separate claims, each wanting 2 of the 4 template
        # chips: both fit one accelerator node.
        clock, store, cloud, mgr = make_harness()
        for i in (1, 2):
            store.create(
                ObjectStore.RESOURCE_CLAIMS,
                ResourceClaim(
                    name=f"c{i}",
                    requests=[DeviceRequest(name="r0", device_class="tpu", count=2)],
                ),
            )
            store.create(ObjectStore.PODS, make_pod(f"p{i}", cpu=1.0, resource_claims=[f"c{i}"]))
        settle(mgr, cloud, store)
        bound = [p for p in store.pods() if p.spec.node_name]
        assert len(bound) == 2
        assert len(store.nodes()) == 1
        c1 = store.get(ObjectStore.RESOURCE_CLAIMS, "c1")
        c2 = store.get(ObjectStore.RESOURCE_CLAIMS, "c2")
        used = {d.device for d in c1.allocation.devices} | {d.device for d in c2.allocation.devices}
        assert len(used) == 4  # disjoint chips

    def test_node_deletion_withdraws_published_slices(self):
        # Counter-set slices carry no node pin but must be withdrawn with
        # the node, or the pool stays permanently incomplete.
        from karpenter_tpu.scheduling.dra import CounterConsumption, CounterSet

        catalog = dra_catalog()
        accel = catalog[1]
        accel.dra_slices[0].shared_counters = [CounterSet(name="hbm", counters={"gb": 64.0})]
        for d in accel.dra_slices[0].devices:
            d.consumes_counters = [CounterConsumption("hbm", {"gb": 16.0})]
        clock, store, cloud, mgr = make_harness(catalog=catalog)
        store.create(
            ObjectStore.RESOURCE_CLAIMS,
            ResourceClaim(name="c", requests=[DeviceRequest(name="r0", device_class="tpu")]),
        )
        store.create(ObjectStore.PODS, make_pod("p", cpu=1.0, resource_claims=["c"]))
        settle(mgr, cloud, store)
        published = store.list(ObjectStore.RESOURCE_SLICES)
        assert len(published) == 2  # device slice + counter-set slice
        node_name = store.get(ObjectStore.PODS, "p").spec.node_name
        store.delete(ObjectStore.NODES, node_name)
        assert store.list(ObjectStore.RESOURCE_SLICES) == []

    def test_template_capacity_forces_second_node(self):
        # Three claims x 2 chips > 4 chips per node: a second node launches.
        clock, store, cloud, mgr = make_harness()
        for i in (1, 2, 3):
            store.create(
                ObjectStore.RESOURCE_CLAIMS,
                ResourceClaim(
                    name=f"c{i}",
                    requests=[DeviceRequest(name="r0", device_class="tpu", count=2)],
                ),
            )
            store.create(ObjectStore.PODS, make_pod(f"p{i}", cpu=1.0, resource_claims=[f"c{i}"]))
        settle(mgr, cloud, store)
        bound = [p for p in store.pods() if p.spec.node_name]
        assert len(bound) == 3
        assert len(store.nodes()) == 2
