"""Mesh sharding: the sharded solve must produce identical results to the
single-device solve (padding types are inert; collectives only reduce)."""

import numpy as np

import jax
import pytest

from karpenter_tpu.parallel import make_mesh, shard_instance_types, sharded_solve


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_mesh_factorization():
    mesh = make_mesh(8)
    assert dict(mesh.shape) == {"dp": 2, "it": 4}
    mesh = make_mesh(4)
    assert dict(mesh.shape) == {"dp": 2, "it": 2}
    mesh = make_mesh(1)
    assert dict(mesh.shape) == {"dp": 1, "it": 1}


def test_mesh_too_few_devices():
    with pytest.raises(ValueError, match="need 16 devices"):
        make_mesh(16)


def test_factorize_mesh():
    from karpenter_tpu.parallel import factorize_mesh

    assert factorize_mesh(8) == (2, 4)
    assert factorize_mesh(4) == (2, 2)
    assert factorize_mesh(16) == (4, 4)
    assert factorize_mesh(6) == (2, 3)
    assert factorize_mesh(7) == (1, 7)
    assert factorize_mesh(1) == (1, 1)


def test_parse_mesh_override():
    from karpenter_tpu.parallel import parse_mesh_override

    assert parse_mesh_override("2x4") == (2, 4)
    assert parse_mesh_override("8X1") == (8, 1)
    for bad in ("", "2x", "x4", "2x4x2", "axb", "0x4", "-1x4", "2.5x2"):
        with pytest.raises(ValueError, match="KTPU_MESH"):
            parse_mesh_override(bad)


def test_mesh_env_override(monkeypatch):
    monkeypatch.setenv("KTPU_MESH", "4x2")
    mesh = make_mesh()
    assert dict(mesh.shape) == {"dp": 4, "it": 2}
    # n_devices consistent with the override is fine
    assert dict(make_mesh(8).shape) == {"dp": 4, "it": 2}


def test_mesh_env_override_validation(monkeypatch):
    monkeypatch.setenv("KTPU_MESH", "3x3")
    with pytest.raises(ValueError, match="have 8"):
        make_mesh()
    monkeypatch.setenv("KTPU_MESH", "2x2")
    with pytest.raises(ValueError, match="caller requested 8"):
        make_mesh(8)
    monkeypatch.setenv("KTPU_MESH", "nope")
    with pytest.raises(ValueError, match="not a valid mesh spec"):
        make_mesh()


def test_sharded_solve_matches_unsharded():
    import __graft_entry__ as ge

    fn, args, meta = ge._build_entry(n_pods=32, n_types=12)
    it = args[8]  # InstanceTypeTensors position in the solve signature
    ref = jax.jit(fn)(*args)
    ref_assignment = np.asarray(ref.assignment)

    mesh = make_mesh(8)
    with mesh:
        it_sharded = shard_instance_types(it, mesh)
        sharded_args = list(args)
        sharded_args[8] = it_sharded
        out = sharded_solve(*sharded_args, **meta)
        out_assignment = np.asarray(out.assignment)

    np.testing.assert_array_equal(ref_assignment, out_assignment)
    assert int(ref.claims.n_open) == int(out.claims.n_open)
    # viable-type sets agree on the real (unpadded) catalog
    T = it.alloc.shape[0]
    np.testing.assert_array_equal(
        np.asarray(ref.claims.its), np.asarray(out.claims.its)[:, :T]
    )
    # padded types never become viable
    assert not np.asarray(out.claims.its)[:, T:].any()


def test_sharded_solve_enforces_min_values():
    """minValues floors must survive sharding: the mv value slab is padded
    alongside the catalog and mv_active threads through sharded_solve."""
    import __graft_entry__ as ge

    fn, args, meta = ge._build_entry(
        n_pods=24, n_types=12, min_values=("karpenter-tpu.sh/instance-family", 2)
    )
    assert meta["mv_active"]
    it = args[8]
    ref = jax.jit(fn)(*args)
    ref_assignment = np.asarray(ref.assignment)

    mesh = make_mesh(8)
    with mesh:
        it_sharded = shard_instance_types(it, mesh)
        sharded_args = list(args)
        sharded_args[8] = it_sharded
        out = sharded_solve(*sharded_args, **meta)
        out_assignment = np.asarray(out.assignment)

    np.testing.assert_array_equal(ref_assignment, out_assignment)
    T = it.alloc.shape[0]
    np.testing.assert_array_equal(
        np.asarray(ref.claims.its), np.asarray(out.claims.its)[:, :T]
    )


def test_dryrun_entry():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


class TestProductionMeshPath:
    """VERDICT r3 #7: the MAIN TPUScheduler.solve shards over the mesh —
    not a parallel twin. Bit-parity with the single-device solve on the
    reference workload mix, through the full encode/dispatch/decode."""

    def _mixed_pods(self, n=64):
        from karpenter_tpu.models import labels as l
        from karpenter_tpu.models.pod import (
            PodAffinityTerm,
            TopologySpreadConstraint,
            make_pod,
        )

        rng = np.random.default_rng(1)
        pods = []
        for i in range(n):
            p = make_pod(
                f"p-{i}",
                cpu=float(rng.choice([0.25, 0.5, 1.0])),
                memory=f"{rng.choice([0.5, 1.0])}Gi",
            )
            if i % 4 == 1:
                p.metadata.labels = {"spread": "zonal"}
                p.spec.topology_spread_constraints = [
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=l.LABEL_TOPOLOGY_ZONE,
                        label_selector={"spread": "zonal"},
                    )
                ]
            elif i % 4 == 2:
                p.metadata.labels = {"app": "web"}
                p.spec.pod_anti_affinity = [
                    PodAffinityTerm(
                        topology_key=l.LABEL_HOSTNAME, label_selector={"app": "web"}
                    )
                ]
            pods.append(p)
        return pods

    def test_scheduler_mesh_bit_parity(self):
        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.controllers.provisioning import (
            TPUScheduler,
            build_templates,
        )
        from karpenter_tpu.models.nodepool import NodePool

        pool = NodePool()
        pool.metadata.name = "default"
        templates = build_templates([(pool, instance_types(50))])
        pods = self._mixed_pods()
        single = TPUScheduler(templates).solve(pods)
        meshed = TPUScheduler(templates, mesh=make_mesh(8)).solve(pods)
        assert not meshed.unschedulable
        assert meshed.assignments == single.assignments
        assert meshed.existing_assignments == single.existing_assignments
        assert len(meshed.claims) == len(single.claims)
        assert abs(meshed.total_price() - single.total_price()) < 1e-9
        for a, b in zip(meshed.claims, single.claims):
            assert [it.name for it in a.instance_types] == [
                it.name for it in b.instance_types
            ]
            assert a.used == b.used
            assert str(a.requirements) == str(b.requirements)

    def test_meshed_whatif_batch_matches_single_device(self):
        """The batched consolidation prefilter on a MESHED scheduler: the
        sharded catalog flows through solve_whatif's vmapped dispatch with
        verdicts identical to the single-device scheduler."""
        from karpenter_tpu.testing import build_bound_cluster, node_candidates

        clock, store, cloud, mgr = build_bound_cluster(n_pods=5, pod_cpu=2.0)
        prov = mgr.provisioner
        candidates = node_candidates(store)
        scenarios = [[c] for c in candidates]
        single = prov.simulate_batch(scenarios)
        assert single is not None
        # rebuild the provisioner's scheduler over the 8-device mesh
        prov.mesh_devices = 8
        prov._scheduler_cache = None
        meshed_sched = prov._build_scheduler()
        assert meshed_sched.mesh is not None
        meshed = prov.simulate_batch(scenarios)
        assert meshed is not None
        assert meshed == single
