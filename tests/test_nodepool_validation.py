"""NodePool runtime validation (nodepool_validation.go:28-58,
nodeclaim_validation.go:66-150, validation controller:61-84)."""

from karpenter_tpu.controllers.status_controllers import (
    NodePoolStatusController,
    NodePoolValidationController,
)
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import (
    CONDITION_READY,
    CONDITION_VALIDATION_SUCCEEDED,
    NodePool,
)
from karpenter_tpu.models.taints import Taint
from karpenter_tpu.models.validation import validate_nodepool
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import FakeClock


def pool_named(name="default") -> NodePool:
    pool = NodePool()
    pool.metadata.name = name
    return pool


class TestValidateNodePool:
    def test_clean_pool_passes(self):
        assert validate_nodepool(pool_named()) == []

    def test_nodepool_label_restricted(self):
        pool = pool_named()
        pool.spec.template.labels[l.NODEPOOL_LABEL_KEY] = "x"
        assert any("restricted" in e for e in validate_nodepool(pool))

    def test_restricted_domain_label(self):
        pool = pool_named()
        pool.spec.template.labels["karpenter.sh/custom"] = "x"
        assert any("not allowed" in e for e in validate_nodepool(pool))

    def test_well_known_label_allowed(self):
        pool = pool_named()
        pool.spec.template.labels[l.CAPACITY_TYPE_LABEL_KEY] = "spot"
        assert validate_nodepool(pool) == []

    def test_bad_label_syntax(self):
        pool = pool_named()
        pool.spec.template.labels["-bad-"] = "v"
        assert any("name part" in e for e in validate_nodepool(pool))
        pool2 = pool_named()
        pool2.spec.template.labels["ok"] = "bad value with spaces"
        assert any("label value" in e for e in validate_nodepool(pool2))

    def test_duplicate_taint_across_lists(self):
        pool = pool_named()
        pool.spec.template.spec.taints = [Taint(key="a", effect="NoSchedule")]
        pool.spec.template.spec.startup_taints = [Taint(key="a", effect="NoSchedule")]
        assert any("duplicate taint" in e for e in validate_nodepool(pool))

    def test_invalid_taint_effect(self):
        pool = pool_named()
        pool.spec.template.spec.taints = [Taint(key="a", effect="Nope")]
        assert any("invalid effect" in e for e in validate_nodepool(pool))

    def test_unsupported_operator(self):
        pool = pool_named()
        pool.spec.template.spec.requirements = [
            {"key": "x", "operator": "Matches", "values": ["a"]}
        ]
        assert any("unsupported operator" in e for e in validate_nodepool(pool))

    def test_gt_requires_single_integer(self):
        pool = pool_named()
        pool.spec.template.spec.requirements = [
            {"key": "cpu-count", "operator": "Gt", "values": ["abc"]}
        ]
        assert any("single integer" in e for e in validate_nodepool(pool))

    def test_min_values_exceeding_values(self):
        pool = pool_named()
        pool.spec.template.spec.requirements = [
            {"key": "x", "operator": "In", "values": ["a"], "minValues": 3}
        ]
        assert any("minValues" in e for e in validate_nodepool(pool))

    def test_requirement_on_nodepool_key_restricted(self):
        pool = pool_named()
        pool.spec.template.spec.requirements = [
            {"key": l.NODEPOOL_LABEL_KEY, "operator": "In", "values": ["p"]}
        ]
        assert any("restricted" in e for e in validate_nodepool(pool))


class TestValidationController:
    def _env(self):
        clock = FakeClock()
        store = ObjectStore(clock)
        return clock, store

    def test_flips_condition_and_gates_ready(self):
        clock, store = self._env()
        bad = pool_named("bad")
        bad.spec.template.labels["karpenter.sh/custom"] = "x"
        good = pool_named("good")
        store.create(ObjectStore.NODEPOOLS, bad)
        store.create(ObjectStore.NODEPOOLS, good)
        assert NodePoolValidationController(store, clock).reconcile() == 1
        assert bad.conditions.is_false(CONDITION_VALIDATION_SUCCEEDED)
        assert good.conditions.is_true(CONDITION_VALIDATION_SUCCEEDED)
        NodePoolStatusController(store, Cluster(clock), clock).reconcile()
        assert bad.conditions.is_false(CONDITION_READY)
        assert good.conditions.is_true(CONDITION_READY)

    def test_invalid_pool_excluded_from_provisioning(self):
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_tpu.controllers.provisioning.provisioner import Provisioner

        clock, store = self._env()
        bad = pool_named("bad")
        bad.spec.template.labels["karpenter.sh/custom"] = "x"
        store.create(ObjectStore.NODEPOOLS, bad)
        NodePoolValidationController(store, clock).reconcile()
        prov = Provisioner(store, Cluster(clock), FakeCloudProvider(), clock)
        assert prov._ready_pools() == []

    def test_fixing_the_pool_restores_readiness(self):
        clock, store = self._env()
        pool = pool_named()
        pool.spec.template.labels["karpenter.sh/custom"] = "x"
        store.create(ObjectStore.NODEPOOLS, pool)
        ctrl = NodePoolValidationController(store, clock)
        ctrl.reconcile()
        assert pool.conditions.is_false(CONDITION_VALIDATION_SUCCEEDED)
        del pool.spec.template.labels["karpenter.sh/custom"]
        store.update(ObjectStore.NODEPOOLS, pool)
        ctrl.reconcile()
        assert pool.conditions.is_true(CONDITION_VALIDATION_SUCCEEDED)
