"""Observability (obs/, ISSUE 12): the round ledger flight recorder and
the compile observatory.

The acceptance properties under test:

- the in-memory ring is BOUNDED by KTPU_LEDGER_RING and the JSONL spill
  rotates at the size cap (never more than SPILL_KEEP rotated files);
- every resident round lands in the ledger with its mode and round-sig,
  and the sig chain stays continuous across full/delta/quarantined
  rounds;
- a remote solve ingests the server's round record over a REAL socket
  (trailing metadata), tagged source="remote";
- a recorded delta round materializes — via the CLI — into a guard
  bundle that ``python -m karpenter_tpu.guard.replay`` re-runs to exit 0
  (bit-identical replay);
- a forced retrace storm is DETECTED: per-kernel compile attribution
  grows, the storm counter fires once, and a Warning event is published;
- the watchdog covers encode and decode with their own stall sections
  and per-section fallback reasons;
- quarantine trips are countable and inspectable (/debug/quarantine,
  TTL gauge);
- recording is cheap enough to stay always-on (<100us per record, far
  under the 1% bench gate).

Everything is CPU-sized for tier-1; the replay subprocess is the one
deliberately slow piece (it is the materialize CLI's contract).
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from karpenter_tpu import guard
from karpenter_tpu.controllers.provisioning import TPUScheduler
from karpenter_tpu.obs import ledger as obs_ledger
from karpenter_tpu.obs import observatory

from test_resident import kind_pods, make_templates, session_scheduler


@pytest.fixture(autouse=True)
def _clean_obs_state(monkeypatch):
    """Every test starts and ends with an empty ledger, the observatory
    disabled, no quarantine, and the obs knobs unset."""
    for var in (
        "KTPU_LEDGER_DIR",
        "KTPU_LEDGER_RING",
        "KTPU_RETRACE_WARN",
        "KTPU_WATCHDOG_S",
        "KTPU_GUARD_AUDIT_RATE",
    ):
        monkeypatch.delenv(var, raising=False)
    obs_ledger.LEDGER.reset()
    observatory.disable()
    observatory.reset()
    guard.QUARANTINE.reset()
    yield
    obs_ledger.LEDGER.reset()
    observatory.disable()
    observatory.reset()
    guard.QUARANTINE.reset()


class TestRing:
    def test_ring_is_bounded_by_env(self, monkeypatch):
        monkeypatch.setenv(obs_ledger.ENV_RING, "8")
        led = obs_ledger.RoundLedger()
        for i in range(20):
            led.record({"mode": "full", "i": i})
        recs = led.records()
        assert len(recs) == 8
        # oldest records aged out; sequence numbering stays continuous
        assert [r["i"] for r in recs] == list(range(12, 20))
        assert [r["seq"] for r in recs] == list(range(13, 21))
        assert led.seq() == 20

    def test_records_n_and_since(self):
        led = obs_ledger.RoundLedger()
        for i in range(5):
            led.record({"i": i})
        assert [r["i"] for r in led.records(2)] == [3, 4]
        assert [r["i"] for r in led.since(3)] == [3, 4]
        assert led.last()["i"] == 4

    def test_record_overhead_stays_flight_recorder_cheap(self):
        """The always-on cost: one dict stamp + deque append. Bench gates
        this against a real solve (<1%); here we pin the absolute cost so
        a regression is visible without the bench."""
        led = obs_ledger.RoundLedger()
        n = 20_000
        t0 = time.perf_counter()
        for i in range(n):
            led.record(
                {"mode": "delta", "reason": "delta", "pods": 64, "wall_s": 0.01}
            )
        per_record = (time.perf_counter() - t0) / n
        assert per_record < 100e-6, f"{per_record * 1e6:.1f}us per record"


class TestSpill:
    def test_jsonl_spill_and_rotation(self, monkeypatch, tmp_path):
        monkeypatch.setenv(obs_ledger.ENV_DIR, str(tmp_path))
        # a tiny cap so a handful of records forces several rotations
        monkeypatch.setattr(obs_ledger, "SPILL_MAX_BYTES", 512)
        led = obs_ledger.RoundLedger()
        for i in range(40):
            led.record({"mode": "full", "reason": "snapshot", "pad": "x" * 64})
        names = sorted(os.listdir(tmp_path))
        assert obs_ledger.SPILL_FILE in names
        assert f"{obs_ledger.SPILL_FILE}.1" in names
        # rotation is capped: never more than SPILL_KEEP rotated files
        assert not any(
            n.startswith(obs_ledger.SPILL_FILE + ".")
            and int(n.rsplit(".", 1)[1]) > obs_ledger.SPILL_KEEP
            for n in names
        )
        spilled = obs_ledger.load_spilled(str(tmp_path))
        assert spilled, "rotated spill must load"
        # oldest-first and torn-tail tolerant
        seqs = [r["seq"] for r in spilled]
        assert seqs == sorted(seqs)
        with open(tmp_path / obs_ledger.SPILL_FILE, "a") as fh:
            fh.write('{"torn": ')
        assert len(obs_ledger.load_spilled(str(tmp_path))) == len(spilled)

    def test_timeline_cli(self, monkeypatch, tmp_path, capsys):
        monkeypatch.setenv(obs_ledger.ENV_DIR, str(tmp_path))
        led = obs_ledger.RoundLedger()
        led.record(
            {"mode": "delta", "reason": "delta", "pods": 12, "wall_s": 0.25,
             "sig": "ab" * 8, "fallback": None}
        )
        assert obs_ledger.main(["--dir", str(tmp_path), "timeline"]) == 0
        out = capsys.readouterr().out
        assert "delta" in out and "ab" * 8 in out and "pods=12" in out


class TestResidentRounds:
    def test_modes_and_sig_chain_across_rounds(self, monkeypatch):
        """full -> delta -> delta -> (trip) quarantined: every round lands
        in the ledger with its mode, a fresh round-sig, and a transcript
        whose base prefix matches the previous round's pod set."""
        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 10)
        session.solve(list(base))
        r1 = obs_ledger.LEDGER.last()
        assert r1["mode"] == "full" and r1["source"] == "local"
        assert r1["sig"] and r1["fpr"]
        assert r1["pods"] == 10

        union = base + kind_pods("b", 4)
        session.solve(list(union))
        r2 = obs_ledger.LEDGER.last()
        assert r2["mode"] == "delta" and r2["seq"] == r1["seq"] + 1
        assert r2["sig"] and r2["sig"] != r1["sig"]
        # the transcript replays the chain: base prefix then the union
        assert r2["transcript"][0] == [str(p.uid) for p in base]
        assert r2["transcript"][-1] == [str(p.uid) for p in union]

        union2 = union + kind_pods("c", 3)
        session.solve(list(union2))
        r3 = obs_ledger.LEDGER.last()
        assert r3["mode"] == "delta" and r3["sig"] not in (r1["sig"], r2["sig"])

        guard.QUARANTINE.trip("resident", reason="test", ttl_s=60.0)
        session.solve(list(union2 + kind_pods("d", 2)))
        r4 = obs_ledger.LEDGER.last()
        assert r4["mode"] == "quarantined" and r4["reason"] == "quarantined"

    def test_quarantined_round_carries_waterfall_and_survives_spill(
        self, monkeypatch, tmp_path, capsys
    ):
        """ISSUE-15 satellite: a quarantined round runs the full
        instrumented path, so its ledger record must carry the waterfall
        and per-phase timings — and mode + gate reason must survive the
        JSONL spill and the timeline CLI's reconstruction."""
        monkeypatch.setenv(obs_ledger.ENV_DIR, str(tmp_path))
        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 10)
        session.solve(list(base))
        guard.QUARANTINE.trip("resident", reason="test", ttl_s=60.0)
        session.solve(list(base + kind_pods("b", 4)))
        rec = obs_ledger.LEDGER.last()
        assert rec["mode"] == "quarantined" and rec["reason"] == "quarantined"
        assert "device_s" in rec, "quarantined rounds keep per-phase timings"
        wf = rec.get("waterfall")
        assert wf, "quarantined rounds run the instrumented full path"
        assert "other" in wf["segments"]
        # telescoping reconciliation: segments (other included) sum to wall
        assert abs(sum(wf["segments"].values()) - wf["wall_s"]) < 1e-3

        spilled = [
            r for r in obs_ledger.load_spilled(str(tmp_path))
            if r.get("seq") == rec["seq"]
        ]
        assert spilled, "quarantined round must spill"
        srec = spilled[-1]
        assert srec["mode"] == "quarantined"
        assert srec["reason"] == "quarantined"
        assert srec.get("waterfall", {}).get("segments")

        code = obs_ledger.main(
            ["--dir", str(tmp_path), "timeline", "--waterfall"]
        )
        assert code == 0
        out = capsys.readouterr().out
        assert "quarantined" in out
        assert "wf_other=" in out
        assert "waterfall wall=" in out  # the ASCII render under the line

    def test_plain_solve_records_one_round(self, monkeypatch):
        sched = TPUScheduler(make_templates(), max_claims=128)
        pods = kind_pods("a", 6)
        seq0 = obs_ledger.LEDGER.seq()
        sched.solve(list(pods))
        recs = obs_ledger.LEDGER.since(seq0)
        assert len(recs) == 1
        rec = recs[0]
        assert rec["mode"] == "full" and rec["outcome"] == "ok"
        assert rec["pods"] == 6 and rec["wall_s"] > 0
        assert rec["fallback"] is None
        assert "device_s" in rec and "stages" in rec

    def test_session_round_is_one_record_not_three(self, monkeypatch):
        """The suppression contract: a resident round's internal full
        solves (snapshot, audit twins) must NOT each add a record — one
        round, one ledger entry."""
        monkeypatch.setenv("KTPU_GUARD_AUDIT_RATE", "1.0")
        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 8)
        session.solve(list(base))
        seq0 = obs_ledger.LEDGER.seq()
        session.solve(list(base + kind_pods("b", 3)))
        recs = obs_ledger.LEDGER.since(seq0)
        assert len(recs) == 1
        assert recs[0]["mode"] == "delta"
        # the sampled shadow audit's verdict rode along
        assert recs[0]["guard"]["verdict"] == "pass"


class TestRemoteIngestion:
    def test_remote_round_arrives_over_a_real_socket(self):
        """The solver service echoes its round record in trailing
        metadata; the client ingests it tagged source="remote" — the
        operator-side ledger sees server rounds without scraping."""
        from karpenter_tpu.rpc import RemoteScheduler, serve

        templates = make_templates()
        server, addr = serve("127.0.0.1:0")
        try:
            remote = RemoteScheduler(addr, templates, max_claims=128)
            base = kind_pods("a", 8)
            remote.solve(list(base))
            remotes = [
                r for r in obs_ledger.LEDGER.records() if r["source"] == "remote"
            ]
            assert remotes, "no remote round ingested from trailing metadata"
            assert remotes[-1]["mode"] in ("full", "delta")
            assert remotes[-1]["pods"] == 8
            seen = len(remotes)
            remote.solve(list(base + kind_pods("b", 4)))
            remotes = [
                r for r in obs_ledger.LEDGER.records() if r["source"] == "remote"
            ]
            assert len(remotes) > seen
            # the resident server round carries its sig chain link
            assert remotes[-1]["sig"]
        finally:
            server.stop(0)


class TestMaterializeReplay:
    def test_ledger_round_materializes_and_replays_clean(
        self, monkeypatch, tmp_path
    ):
        """The incident workflow end to end: record a delta round with
        spill on, materialize it through the CLI, and guard.replay must
        re-run the transcript bit-identically (exit 0)."""
        monkeypatch.setenv(obs_ledger.ENV_DIR, str(tmp_path))
        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 10)
        session.solve(list(base))
        session.solve(list(base + kind_pods("b", 5)))
        rec = obs_ledger.LEDGER.last()
        assert rec["mode"] == "delta"
        assert rec["capsule"], "spill-enabled delta round must write a capsule"
        assert (tmp_path / rec["capsule"]).exists()

        out = tmp_path / "repro.json"
        code = obs_ledger.main(
            ["--dir", str(tmp_path), "materialize", str(rec["seq"]),
             "--out", str(out)]
        )
        assert code == 0
        doc = json.loads(out.read_text())
        assert doc["path"] == "resident"
        assert doc["rounds"] == rec["transcript"]
        assert doc["detail"]["ledger_seq"] == rec["seq"]

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-m", "karpenter_tpu.guard.replay", str(out)],
            capture_output=True,
            text=True,
            timeout=420,
            env=env,
        )
        assert proc.returncode == 0, proc.stderr + proc.stdout

    def test_materialize_without_capsule_is_a_clear_error(self, tmp_path):
        rec = {"seq": 7, "mode": "full"}
        with pytest.raises(ValueError, match="no capsule"):
            obs_ledger.materialize_record(rec, str(tmp_path))


class TestObservatory:
    def test_forced_retrace_storm_is_detected(self, monkeypatch):
        """A kernel recompiled past KTPU_RETRACE_WARN (growing shapes —
        the classic pad-bucket churn) must grow its per-kernel compile
        attribution, fire the storm counter ONCE, and publish a Warning
        event through the guard recorder."""
        import jax
        import jax.numpy as jnp

        from karpenter_tpu.guard import config as guard_config
        from karpenter_tpu.utils.events import Recorder
        from karpenter_tpu.utils.metrics import JIT_COMPILES, JIT_RETRACE_STORMS

        monkeypatch.setenv(observatory.ENV_RETRACE_WARN, "2")
        recorder = Recorder()
        old = guard_config.event_recorder()
        guard_config.set_event_recorder(recorder)
        try:
            observatory.enable()

            @observatory.named_kernel("obs_test_kernel")
            @jax.jit
            def bump(x):
                return x + 1

            c0 = JIT_COMPILES.get(kernel="obs_test_kernel")
            s0 = JIT_RETRACE_STORMS.get(kernel="obs_test_kernel")
            for n in range(1, 5):  # four shapes -> four traces
                bump(jnp.zeros((n,), jnp.float32)).block_until_ready()
            assert JIT_COMPILES.get(kernel="obs_test_kernel") == c0 + 4
            snap = observatory.snapshot()
            assert snap["obs_test_kernel"]["compiles"] == 4
            assert snap["obs_test_kernel"]["seconds"] > 0
            # the storm fired exactly once, not once per extra compile
            assert JIT_RETRACE_STORMS.get(kernel="obs_test_kernel") == s0 + 1
            # other kernels (e.g. anonymous jnp.zeros traces) may storm
            # too; the contract is ONE event for the named kernel
            storms = [
                e
                for e in recorder.events
                if e.reason == "RetraceStorm" and e.name == "obs_test_kernel"
            ]
            assert len(storms) == 1
            assert storms[0].type == "Warning"
            assert "obs_test_kernel" in storms[0].message
        finally:
            guard_config.set_event_recorder(old)

    def test_disabled_observatory_attributes_nothing(self):
        import jax
        import jax.numpy as jnp

        @observatory.named_kernel("obs_dark_kernel")
        @jax.jit
        def bump(x):
            return x + 1

        bump(jnp.zeros((3,), jnp.float32)).block_until_ready()
        assert "obs_dark_kernel" not in observatory.snapshot()
        assert observatory.drain_notes() == []

    def test_kernel_scope_names_anonymous_compiles(self):
        """Compiles triggered by host helpers jitted OUTSIDE a
        named_kernel entry point used to land in the `anonymous` bucket
        (ISSUE 14 satellite): inside a kernel_scope they inherit the
        scope's name, while a nested named_kernel still wins."""
        import jax
        import jax.numpy as jnp

        from karpenter_tpu.utils.metrics import JIT_COMPILES

        observatory.enable()

        @jax.jit
        def helper(x):
            return x * 2

        @observatory.named_kernel("obs_scoped_kernel")
        @jax.jit
        def named(x):
            return x + 1

        s0 = JIT_COMPILES.get(kernel="obs_scope_round")
        n0 = JIT_COMPILES.get(kernel="obs_scoped_kernel")
        with observatory.kernel_scope("obs_scope_round"):
            helper(jnp.zeros((5,), jnp.float32)).block_until_ready()
            named(jnp.zeros((5,), jnp.float32)).block_until_ready()
        # the helper's compile (plus any anonymous array-building traces
        # inside the block) lands under the scope's name...
        assert JIT_COMPILES.get(kernel="obs_scope_round") >= s0 + 1
        # ...while the named kernel keeps exactly its own compile
        assert JIT_COMPILES.get(kernel="obs_scoped_kernel") == n0 + 1
        snap = observatory.snapshot()
        assert snap["obs_scope_round"]["compiles"] >= 1
        assert snap["obs_scoped_kernel"]["compiles"] == 1

    def test_solve_round_scope_claims_helper_compiles(self):
        """A fresh scheduler's solve compiles helper executables (chunk
        gathers, fetch preps) outside any named_kernel; the solve-round
        scope must claim them so nothing attributes to `anonymous`."""
        observatory.enable()
        sched = TPUScheduler(make_templates(), max_claims=128)
        sched.solve(list(kind_pods("scope", 6)))
        snap = observatory.snapshot()
        assert "solve_round" in snap, sorted(snap)
        assert "anonymous" not in snap, sorted(snap)

    def test_compile_notes_fold_into_the_ledger(self, monkeypatch):
        """A solve that compiles while the observatory is on carries the
        per-kernel compile notes in its ledger record."""
        observatory.enable()
        sched = TPUScheduler(make_templates(), max_claims=128)
        sched.solve(list(kind_pods("a", 6)))
        rec = obs_ledger.LEDGER.last()
        compiles = rec.get("compiles") or []
        assert compiles, "fresh-scheduler solve must record compile notes"
        assert {"kernel", "seconds"} <= set(compiles[0])
        kernels = {c["kernel"] for c in compiles}
        # helper compiles attribute to the round scope now, not anonymous
        assert kernels & {
            "solve", "solve_fill", "global_template", "solve_round"
        }


class TestWatchdogSections:
    def _stalled(self, monkeypatch, method, section):
        from karpenter_tpu.utils.metrics import SOLVER_FALLBACK, WATCHDOG_STALLS

        monkeypatch.setenv("KTPU_WATCHDOG_S", "0.3")
        orig = getattr(TPUScheduler, method)

        def slow(self, *args, **kwargs):
            time.sleep(1.2)
            return orig(self, *args, **kwargs)

        monkeypatch.setattr(TPUScheduler, method, slow)
        sched = TPUScheduler(make_templates(), max_claims=128)
        pods = kind_pods("a", 8)
        stalls0 = WATCHDOG_STALLS.get(section=section)
        fb0 = SOLVER_FALLBACK.get(reason=f"watchdog_{section}")
        r = sched.solve(list(pods))
        assert WATCHDOG_STALLS.get(section=section) == stalls0 + 1
        assert SOLVER_FALLBACK.get(reason=f"watchdog_{section}") == fb0 + 1
        assert not r.unschedulable
        assert set(r.assignments) == {p.uid for p in pods}
        # the ledger recorded the degradation rung
        rec = obs_ledger.LEDGER.last()
        assert rec["fallback"] == f"watchdog_{section}"
        assert rec["reason"] == f"watchdog_{section}"

    def test_stalled_encode_falls_back_per_section(self, monkeypatch):
        self._stalled(monkeypatch, "_encode", "encode")

    def test_stalled_decode_falls_back_per_section(self, monkeypatch):
        self._stalled(monkeypatch, "_decode", "decode")


class TestQuarantineInspection:
    def test_trips_ttl_and_state(self):
        from karpenter_tpu.utils.metrics import GUARD_QUARANTINE_TTL

        guard.QUARANTINE.trip("resident", reason="audit divergence", ttl_s=60.0)
        guard.QUARANTINE.trip("grid", reason="test", ttl_s=30.0)
        assert GUARD_QUARANTINE_TTL.get(path="resident") == 60.0
        st = guard.QUARANTINE.state()
        assert st["resident"]["active"] and st["resident"]["trips"] == 1
        assert st["resident"]["reason"] == "audit divergence"
        assert 0 < st["resident"]["ttl_s"] <= 60.0
        guard.QUARANTINE.clear("grid")
        assert GUARD_QUARANTINE_TTL.get(path="grid") == 0
        st = guard.QUARANTINE.state()
        # the all-time trip count survives the clear
        assert not st["grid"]["active"] and st["grid"]["trips"] == 1
        guard.QUARANTINE.trip("grid", reason="again", ttl_s=30.0)
        assert guard.QUARANTINE.state()["grid"]["trips"] == 2


class TestDebugEndpoints:
    def _get(self, port, path, timeout=10):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout
        ) as resp:
            return resp.status, resp.read().decode()

    def test_rounds_quarantine_profile_endpoints(self, monkeypatch, tmp_path):
        from karpenter_tpu.utils.runtime import HealthConfig, serve_health

        monkeypatch.setenv(obs_ledger.ENV_DIR, str(tmp_path))
        obs_ledger.LEDGER.record(
            {"mode": "full", "reason": "snapshot", "pods": 3, "wall_s": 0.1}
        )
        guard.QUARANTINE.trip("resident", reason="test", ttl_s=60.0)
        server, port = serve_health(HealthConfig(enable_profiling=True))
        try:
            status, body = self._get(port, "/debug/rounds?n=1")
            assert status == 200
            payload = json.loads(body)
            assert payload["rounds"][-1]["mode"] == "full"
            assert "observatory" in payload

            status, body = self._get(port, "/debug/quarantine")
            assert status == 200
            assert json.loads(body)["resident"]["active"]

            # late in a long-lived process the trace serialization walks
            # every compiled module, so the capture can take far longer
            # than the 0.05s window — give the request a wide deadline
            status, body = self._get(
                port, "/debug/profile?seconds=0.05", timeout=180
            )
            assert status == 200
            payload = json.loads(body)
            assert payload["dir"].startswith(str(tmp_path))
            assert payload["files"], "profile capture wrote no files"
        finally:
            server.shutdown()

    def test_endpoints_are_404_without_profiling(self):
        from karpenter_tpu.utils.runtime import HealthConfig, serve_health

        server, port = serve_health(HealthConfig(enable_profiling=False))
        try:
            for path in ("/debug/rounds", "/debug/quarantine", "/debug/profile"):
                with pytest.raises(urllib.error.HTTPError) as err:
                    self._get(port, path)
                assert err.value.code == 404
        finally:
            server.shutdown()
