"""Topology semantics: spread skew, pod affinity, pod anti-affinity —
behavioral parity with reference topology_test.go expectations (ExpectSkew
analog) on the host scheduler."""

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.controllers.provisioning import HostScheduler, build_templates
from karpenter_tpu.controllers.provisioning.topology import (
    Topology,
    build_universe_domains,
)
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import PodAffinityTerm, TopologySpreadConstraint, make_pod


def default_pool(name="default"):
    pool = NodePool()
    pool.metadata.name = name
    return pool


def spread_pods(n, key, max_skew=1, labels=None, cpu=0.5):
    labels = labels or {"app": "web"}
    pods = []
    for i in range(n):
        p = make_pod(f"sp-{i}", cpu=cpu)
        p.metadata.labels = dict(labels)
        p.spec.topology_spread_constraints = [
            TopologySpreadConstraint(max_skew=max_skew, topology_key=key, label_selector=dict(labels))
        ]
        pods.append(p)
    return pods


def build_host(pods, n_types=32, templates=None):
    templates = templates or build_templates([(default_pool(), instance_types(n_types))])
    universe = build_universe_domains(templates)
    topo = Topology.build(pods, universe)
    return HostScheduler(templates, topology=topo), templates


def zone_distribution(result):
    dist = {}
    for c in result.claims:
        zone_req = c.requirements.get(l.LABEL_TOPOLOGY_ZONE)
        zones = sorted(zone_req.values)
        assert len(zones) == 1, f"claim zone not collapsed: {zones}"
        dist[zones[0]] = dist.get(zones[0], 0) + len(c.pods)
    return dist


class TestZonalSpread:
    def test_even_spread_across_zones(self):
        pods = spread_pods(12, l.LABEL_TOPOLOGY_ZONE)
        host, _ = build_host(pods)
        result = host.solve(pods)
        assert not result.unschedulable
        dist = zone_distribution(result)
        # 4 zones in the fake catalog; 12 pods -> 3 per zone at maxSkew 1
        assert len(dist) == 4
        assert max(dist.values()) - min(dist.values()) <= 1

    def test_uneven_count_respects_skew(self):
        pods = spread_pods(10, l.LABEL_TOPOLOGY_ZONE)
        host, _ = build_host(pods)
        result = host.solve(pods)
        dist = zone_distribution(result)
        assert sum(dist.values()) == 10
        assert max(dist.values()) - min(dist.values()) <= 1

    def test_spread_with_max_skew_2(self):
        pods = spread_pods(8, l.LABEL_TOPOLOGY_ZONE, max_skew=2)
        host, _ = build_host(pods)
        result = host.solve(pods)
        dist = zone_distribution(result)
        assert max(dist.values()) - min(dist.values()) <= 2

    def test_unrelated_pods_dont_count(self):
        spread = spread_pods(4, l.LABEL_TOPOLOGY_ZONE)
        others = [make_pod(f"other-{i}", cpu=0.5) for i in range(6)]
        host, _ = build_host(spread + others)
        result = host.solve(spread + others)
        assert not result.unschedulable
        # only the 4 labeled pods spread; distribution over them is even
        counts = {}
        for c in result.claims:
            n = sum(1 for p in c.pods if p.metadata.labels.get("app") == "web")
            if n:
                zone = sorted(c.requirements.get(l.LABEL_TOPOLOGY_ZONE).values)[0]
                counts[zone] = counts.get(zone, 0) + n
        assert sum(counts.values()) == 4
        assert max(counts.values()) - min(counts.values()) <= 1


class TestHostnameSpread:
    def test_one_pod_per_node(self):
        pods = spread_pods(5, l.LABEL_HOSTNAME, max_skew=1)
        host, _ = build_host(pods, n_types=64)
        result = host.solve(pods)
        assert not result.unschedulable
        # hostname spread with skew 1: since a new node is always creatable
        # (global min 0), each claim holds at most 1 matching pod
        for c in result.claims:
            matching = [p for p in c.pods if p.metadata.labels.get("app") == "web"]
            assert len(matching) <= 1
        assert len(result.claims) == 5


class TestPodAntiAffinity:
    def test_zone_anti_affinity_with_zone_selectors(self):
        """Reference 'should not violate pod anti-affinity on zone'
        (topology_test.go:2319): zone-pinned pods collapse their claims, so
        self-anti-affinity separates them; a fourth floating pod is blocked
        because every zone has a matching pod."""
        pods = []
        for i, zone in enumerate(["test-zone-1", "test-zone-2", "test-zone-3"]):
            p = make_pod(f"aa-{i}", cpu=2.0, node_selector={l.LABEL_TOPOLOGY_ZONE: zone})
            p.metadata.labels = {"security": "s2"}
            pods.append(p)
        aff = make_pod("aff", cpu=0.25)
        aff.spec.pod_anti_affinity = [
            PodAffinityTerm(topology_key=l.LABEL_TOPOLOGY_ZONE, label_selector={"security": "s2"})
        ]
        host, _ = build_host(pods + [aff])
        result = host.solve(pods + [aff])
        dist = zone_distribution(result)
        assert dist.get("test-zone-4", 0) >= 0  # zone-4 is the only free zone
        # the three pinned pods scheduled; aff only fits zone-4
        assert {"test-zone-1", "test-zone-2", "test-zone-3"} <= set(dist)
        aff_claims = [c for c in result.claims if any(p.name == "aff" for p in c.pods)]
        assert len(aff_claims) == 1
        assert sorted(aff_claims[0].requirements.get(l.LABEL_TOPOLOGY_ZONE).values) == ["test-zone-4"]

    def test_schroedinger_blocks_same_pass(self):
        """Reference 'Schrödinger' case (topology_test.go:2499): an
        anti-affinity owner whose zone never collapses records every zone,
        blocking matching pods within the same Solve."""
        anywhere = make_pod("anywhere", cpu=2.0)
        anywhere.spec.pod_anti_affinity = [
            PodAffinityTerm(topology_key=l.LABEL_TOPOLOGY_ZONE, label_selector={"security": "s2"})
        ]
        target = make_pod("target", cpu=0.25)
        target.metadata.labels = {"security": "s2"}
        host, _ = build_host([anywhere, target])
        result = host.solve([anywhere, target])
        assert [p.name for p, _ in result.unschedulable] == ["target"]

    def test_self_anti_affinity_zone_first_pass(self):
        """Self-anti-affinity without zone pins: the first owner takes all
        (uncollapsed) zones; the rest defer to later passes."""
        pods = []
        for i in range(3):
            p = make_pod(f"aa-{i}", cpu=0.5)
            p.metadata.labels = {"app": "db"}
            p.spec.pod_anti_affinity = [
                PodAffinityTerm(topology_key=l.LABEL_TOPOLOGY_ZONE, label_selector={"app": "db"})
            ]
            pods.append(p)
        host, _ = build_host(pods)
        result = host.solve(pods)
        assert len(result.unschedulable) == 2

    def test_hostname_anti_affinity(self):
        pods = []
        for i in range(4):
            p = make_pod(f"ha-{i}", cpu=0.25)
            p.metadata.labels = {"app": "db"}
            p.spec.pod_anti_affinity = [
                PodAffinityTerm(topology_key=l.LABEL_HOSTNAME, label_selector={"app": "db"})
            ]
            pods.append(p)
        host, _ = build_host(pods, n_types=64)
        result = host.solve(pods)
        assert not result.unschedulable
        for c in result.claims:
            assert len([p for p in c.pods if p.metadata.labels.get("app") == "db"]) == 1

    def test_inverse_anti_affinity_blocks_matched_pods(self):
        """A zone-pinned pod with anti-affinity against app=web blocks
        app=web pods from that zone only (inverse groups)."""
        guard = make_pod(
            "guard", cpu=4.0, node_selector={l.LABEL_TOPOLOGY_ZONE: "test-zone-1"}
        )  # big: FFD places it first
        guard.metadata.labels = {"role": "guard"}
        guard.spec.pod_anti_affinity = [
            PodAffinityTerm(topology_key=l.LABEL_TOPOLOGY_ZONE, label_selector={"app": "web"})
        ]
        web = make_pod("web", cpu=0.25)
        web.metadata.labels = {"app": "web"}
        pods = [guard, web]
        host, _ = build_host(pods)
        result = host.solve(pods)
        assert not result.unschedulable
        by_name = {}
        for c in result.claims:
            zone = sorted(c.requirements.get(l.LABEL_TOPOLOGY_ZONE).values)
            for p in c.pods:
                by_name[p.name] = zone
        assert by_name["guard"] == ["test-zone-1"]
        assert "test-zone-1" not in by_name["web"]


class TestUniverseDomains:
    def test_notin_exclusions_not_in_universe(self):
        """A NodePool excluding a zone must not leave that zone in the
        universe as a permanently-empty domain (pins spread min at 0)."""
        pool = default_pool()
        pool.spec.template.spec.requirements = [
            {"key": l.LABEL_TOPOLOGY_ZONE, "operator": "NotIn", "values": ["test-zone-4"]}
        ]
        templates = build_templates([(pool, instance_types(32))])
        universe = build_universe_domains(templates)
        assert "test-zone-4" not in universe[l.LABEL_TOPOLOGY_ZONE]
        pods = spread_pods(6, l.LABEL_TOPOLOGY_ZONE)
        topo = Topology.build(pods, universe)
        host = HostScheduler(templates, topology=topo)
        result = host.solve(pods)
        assert not result.unschedulable
        dist = zone_distribution(result)
        assert set(dist) == {"test-zone-1", "test-zone-2", "test-zone-3"}
        assert max(dist.values()) - min(dist.values()) <= 1

    def test_schedule_anyway_tsc_is_soft(self):
        pods = spread_pods(6, l.LABEL_TOPOLOGY_ZONE)
        for p in pods:
            p.spec.topology_spread_constraints[0].when_unsatisfiable = "ScheduleAnyway"
        host, _ = build_host(pods)
        result = host.solve(pods)
        assert not result.unschedulable

    def test_inverse_namespace_isolation(self):
        """Anti-affinity enforcement must work in any namespace."""
        anywhere = make_pod("anywhere", cpu=2.0)
        anywhere.metadata.namespace = "prod"
        anywhere.spec.pod_anti_affinity = [
            PodAffinityTerm(topology_key=l.LABEL_TOPOLOGY_ZONE, label_selector={"security": "s2"})
        ]
        target = make_pod("target", cpu=0.25)
        target.metadata.namespace = "prod"
        target.metadata.labels = {"security": "s2"}
        host, _ = build_host([anywhere, target])
        result = host.solve([anywhere, target])
        assert [p.name for p, _ in result.unschedulable] == ["target"]


class TestPodAffinity:
    def test_affinity_colocates(self):
        leader = make_pod("leader", cpu=2.0)
        leader.metadata.labels = {"app": "cache"}
        leader.spec.pod_affinity = [
            PodAffinityTerm(topology_key=l.LABEL_TOPOLOGY_ZONE, label_selector={"app": "cache"})
        ]
        followers = []
        for i in range(3):
            p = make_pod(f"f-{i}", cpu=0.25)
            p.metadata.labels = {"app": "cache"}
            p.spec.pod_affinity = [
                PodAffinityTerm(topology_key=l.LABEL_TOPOLOGY_ZONE, label_selector={"app": "cache"})
            ]
            followers.append(p)
        pods = [leader] + followers
        host, _ = build_host(pods)
        result = host.solve(pods)
        assert not result.unschedulable
        dist = zone_distribution(result)
        assert len(dist) == 1  # all in one zone

    def test_affinity_to_absent_pods_unschedulable(self):
        p = make_pod("lonely", cpu=0.5)
        p.metadata.labels = {"app": "x"}  # does NOT match the selector
        p.spec.pod_affinity = [
            PodAffinityTerm(topology_key=l.LABEL_TOPOLOGY_ZONE, label_selector={"app": "absent"})
        ]
        host, _ = build_host([p])
        result = host.solve([p])
        assert len(result.unschedulable) == 1
