"""Daemon overhead, PDBs, cost ledger, pool health, Balanced scoring,
NodeOverlay."""

import pytest

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.cloudprovider.overlay import NodeOverlay, OverlayCloudProvider
from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.daemonset import DaemonSet
from karpenter_tpu.models.nodepool import Budget, NodePool
from karpenter_tpu.models.pdb import PodDisruptionBudget, blocked_pod_uids
from karpenter_tpu.models.pod import PodSpec, make_pod
from karpenter_tpu.state.cost import ClusterCost, NodePoolHealth
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.clock import FakeClock


def build_env(catalog_size=50):
    clock = FakeClock()
    store = ObjectStore(clock)
    cloud = KwokCloudProvider(store, catalog=instance_types(catalog_size))
    mgr = Manager(store, cloud, clock)
    pool = NodePool()
    pool.metadata.name = "default"
    pool.spec.disruption.budgets = [Budget(nodes="100%")]
    store.create(ObjectStore.NODEPOOLS, pool)
    return clock, store, cloud, mgr


def provision(mgr, store, cloud, pods):
    for p in pods:
        store.create(ObjectStore.PODS, p)
    mgr.run_until_idle()
    cloud.simulate_kubelet_ready()
    mgr.run_until_idle()
    KubeSchedulerSim(store, mgr.cluster).bind_pending()
    mgr.run_until_idle()


class TestDaemonOverhead:
    def test_daemon_requests_reserve_capacity(self):
        clock, store, cloud, mgr = build_env(catalog_size=8)  # 1-cpu shapes
        ds = DaemonSet()
        ds.metadata.name = "log-agent"
        ds.pod_template = PodSpec(requests={res.CPU: 0.5, res.MEMORY: float(2**28)})
        store.create(ObjectStore.DAEMONSETS, ds)
        # a 0.5-cpu pod + 0.5-cpu daemon cannot share a 1-cpu node
        # (allocatable ~0.92), so each pod needs its own node and a second
        # 0.5 pod cannot squeeze onto the first node
        provision(mgr, store, cloud, [make_pod(f"p-{i}", cpu=0.25) for i in range(2)])
        claims = store.nodeclaims()
        assert claims
        for c in claims:
            # claim requests include the daemon overhead
            assert c.spec.requests.get("cpu", 0) >= 0.5

    def test_intolerant_daemon_not_counted(self):
        from karpenter_tpu.models.taints import NO_SCHEDULE, Taint

        clock, store, cloud, mgr = build_env()
        pool = store.get(ObjectStore.NODEPOOLS, "default")
        pool.spec.template.spec.taints = [Taint(key="dedicated", value="x", effect=NO_SCHEDULE)]
        store.update(ObjectStore.NODEPOOLS, pool)
        ds = DaemonSet()
        ds.pod_template = PodSpec(requests={res.CPU: 8.0})  # huge, but intolerant
        store.create(ObjectStore.DAEMONSETS, ds)
        from karpenter_tpu.models.taints import Toleration

        pod = make_pod("p", cpu=0.5)
        pod.spec.tolerations = [Toleration(key="dedicated", operator="Exists")]
        provision(mgr, store, cloud, [pod])
        claims = store.nodeclaims()
        assert claims
        assert claims[0].spec.requests.get("cpu", 0) < 2.0  # daemon not added

    def test_per_instance_type_signature_groups(self):
        """A nodeSelector'd daemonset charges ONLY the instance types it
        can land on (buildDaemonOverheadGroups scheduler.go:963-1043): the
        template splits into per-group virtual templates, so a pod placed
        on a non-matching type is not billed the daemon's requests."""
        from karpenter_tpu.models import labels as l

        clock, store, cloud, mgr = build_env(catalog_size=16)
        ds = DaemonSet()
        ds.metadata.name = "amd-only-agent"
        ds.pod_template = PodSpec(
            requests={res.CPU: 0.5},
            node_selector={l.LABEL_ARCH: l.ARCH_ARM64},
        )
        store.create(ObjectStore.DAEMONSETS, ds)
        templates = mgr.provisioner._build_scheduler().templates
        # the split produced one group charging the daemon (arm64 types)
        # and one charging nothing (the rest of the catalog)
        charged = [t for t in templates if t.daemon_requests.get(res.CPU)]
        uncharged = [t for t in templates if not t.daemon_requests.get(res.CPU)]
        assert charged and uncharged
        for t in charged:
            for it in t.instance_types:
                assert l.ARCH_ARM64 in it.requirements.get(l.LABEL_ARCH).values
        for t in uncharged:
            for it in t.instance_types:
                assert l.ARCH_ARM64 not in it.requirements.get(l.LABEL_ARCH).values
        # an amd64-pinned pod schedules WITHOUT the daemon overhead
        pod = make_pod("p", cpu=0.25, node_selector={l.LABEL_ARCH: l.ARCH_AMD64})
        provision(mgr, store, cloud, [pod])
        claims = store.nodeclaims()
        assert claims
        assert claims[0].spec.requests.get("cpu", 0) < 0.5 + 0.25

    def test_or_term_relaxation_reaches_later_terms(self):
        """Daemon compatibility retries dropped OR terms
        (scheduler.go:1035-1041 removeRequiredNodeAffinityTerm): a daemon
        whose FIRST term matches nothing but whose second matches the pool
        still charges overhead."""
        from karpenter_tpu.models import labels as l
        from karpenter_tpu.models.pod import NodeAffinity, NodeSelectorTerm

        clock, store, cloud, mgr = build_env(catalog_size=8)
        ds = DaemonSet()
        ds.metadata.name = "fallback-agent"
        tmpl = PodSpec(requests={res.CPU: 0.5})
        tmpl.node_affinity = NodeAffinity(
            required=[
                NodeSelectorTerm(
                    match_expressions=[
                        {"key": l.LABEL_TOPOLOGY_ZONE, "operator": "In",
                         "values": ["zone-nowhere"]}
                    ]
                ),
                NodeSelectorTerm(match_expressions=[]),  # matches anything
            ]
        )
        ds.pod_template = tmpl
        store.create(ObjectStore.DAEMONSETS, ds)
        templates = mgr.provisioner._build_scheduler().templates
        assert all(t.daemon_requests.get(res.CPU) == 0.5 for t in templates)


class TestPDB:
    def test_blocked_pods(self):
        pdb = PodDisruptionBudget(selector={"app": "db"}, min_available="2")
        pods = []
        for i in range(2):
            p = make_pod(f"db-{i}")
            p.metadata.labels = {"app": "db"}
            p.spec.node_name = f"node-{i}"
            pods.append(p)
        blocked = blocked_pod_uids([pdb], pods)
        assert len(blocked) == 2  # 2 healthy, min 2 -> zero budget

    def test_max_unavailable_allows(self):
        pdb = PodDisruptionBudget(selector={"app": "db"}, max_unavailable="1")
        p = make_pod("db-0")
        p.metadata.labels = {"app": "db"}
        p.spec.node_name = "n"
        assert blocked_pod_uids([pdb], [p]) == set()

    def test_pdb_blocks_disruption(self):
        clock, store, cloud, mgr = build_env()
        pod = make_pod("db", cpu=1.0)
        pod.metadata.labels = {"app": "db"}
        provision(mgr, store, cloud, [pod])
        store.create(
            ObjectStore.PDBS,
            PodDisruptionBudget(selector={"app": "db"}, min_available="1"),
        )
        clock.step(60.0)
        # the node hosts a PDB-protected pod: no disruption command
        assert mgr.run_disruption_once() is None


class TestCostAndHealth:
    def test_cost_ledger_tracks_pools(self):
        cost = ClusterCost()
        cost.set_claim("a", "c1", 1.5)
        cost.set_claim("a", "c2", 0.5)
        cost.set_claim("b", "c3", 2.0)
        assert cost.pool_cost("a") == 2.0
        assert cost.total() == 4.0
        cost.remove_claim("a", "c1")
        assert cost.pool_cost("a") == 0.5

    def test_pool_health_ring(self):
        h = NodePoolHealth(capacity=4)
        assert h.healthy("p") is None
        h.record("p", True)
        assert h.healthy("p") is True
        h.record("p", False)
        assert h.healthy("p") is True  # 1/4 failures < 50%
        h.record("p", False)
        assert h.healthy("p") is False  # 2/4 failures hits the threshold
        for _ in range(4):
            h.record("p", True)
        assert h.healthy("p") is True  # window rolled over

    def test_cost_updates_from_lifecycle(self):
        clock, store, cloud, mgr = build_env()
        provision(mgr, store, cloud, [make_pod("p", cpu=1.0)])
        assert mgr.cost.pool_cost("default") > 0
        assert mgr.pool_health.healthy("default") is True
        # retire the pod first so the drained claim isn't replaced
        pod = store.get(ObjectStore.PODS, "p")
        pod.status.phase = "Succeeded"
        store.update(ObjectStore.PODS, pod)
        store.delete(ObjectStore.PODS, pod.name)
        claim = store.nodeclaims()[0]
        store.delete(ObjectStore.NODECLAIMS, claim.name)
        mgr.run_until_idle()
        assert mgr.cost.pool_cost("default") == 0


class TestBalanced:
    def test_balanced_pool_blocks_low_value_move(self):
        """With Balanced policy, a move whose savings/disruption ratio is
        poor must not execute."""
        clock, store, cloud, mgr = build_env(catalog_size=64)
        pool = store.get(ObjectStore.NODEPOOLS, "default")
        pool.spec.disruption.consolidation_policy = "Balanced"
        pool.spec.template.spec.requirements = [
            {
                "key": l.CAPACITY_TYPE_LABEL_KEY,
                "operator": "In",
                "values": [l.CAPACITY_TYPE_ON_DEMAND],
            }
        ]
        store.update(ObjectStore.NODEPOOLS, pool)
        # many pods with high deletion costs -> disruption dwarfs savings
        pods = []
        for i in range(8):
            p = make_pod(f"p-{i}", cpu=1.5, memory="1Gi")
            p.metadata.annotations["controller.kubernetes.io/pod-deletion-cost"] = "100000"
            pods.append(p)
        provision(mgr, store, cloud, pods)
        # shrink usage: replacement would save a little but disrupt a lot
        for pod in list(store.pods()):
            if pod.name not in ("p-0", "p-1"):
                pod.status.phase = "Succeeded"
                store.update(ObjectStore.PODS, pod)
                store.delete(ObjectStore.PODS, pod.name)
        mgr.run_until_idle()
        clock.step(60.0)
        for _ in range(3):
            cmd = mgr.run_disruption_once()
            assert cmd is None or not cmd.candidates, "Balanced pool approved a bad move"
            clock.step(20.0)


class TestNodeOverlay:
    def test_price_overlay_applies(self):
        clock = FakeClock()
        store = ObjectStore(clock)
        inner = KwokCloudProvider(store, catalog=instance_types(8))
        cloud = OverlayCloudProvider(inner, store)
        overlay = NodeOverlay(
            requirements=[{"key": l.LABEL_ARCH, "operator": "In", "values": [l.ARCH_AMD64]}],
            price="+100%",
        )
        overlay.metadata.name = "double-amd64"
        store.create(ObjectStore.NODE_OVERLAYS, overlay)
        pool = NodePool()
        base = {it.name: it for it in inner.get_instance_types(pool)}
        for it in cloud.get_instance_types(pool):
            orig = base[it.name]
            arch = it.requirements.get(l.LABEL_ARCH).any_value()
            for of, of0 in zip(it.offerings, orig.offerings):
                if arch == l.ARCH_AMD64:
                    assert of.price == pytest.approx(of0.price * 2)
                    assert of.is_price_overlaid
                else:
                    assert of.price == of0.price

    def test_spot_only_overlay_leaves_on_demand_alone(self):
        clock = FakeClock()
        store = ObjectStore(clock)
        inner = KwokCloudProvider(store, catalog=instance_types(8))
        cloud = OverlayCloudProvider(inner, store)
        overlay = NodeOverlay(
            requirements=[
                {
                    "key": l.CAPACITY_TYPE_LABEL_KEY,
                    "operator": "In",
                    "values": [l.CAPACITY_TYPE_SPOT],
                }
            ],
            price="-50%",
        )
        overlay.metadata.name = "spot-discount"
        store.create(ObjectStore.NODE_OVERLAYS, overlay)
        pool = NodePool()
        base = {it.name: it for it in inner.get_instance_types(pool)}
        for it in cloud.get_instance_types(pool):
            for of, of0 in zip(it.offerings, base[it.name].offerings):
                if of.capacity_type == l.CAPACITY_TYPE_SPOT:
                    assert of.price == pytest.approx(of0.price * 0.5)
                else:
                    assert of.price == of0.price
                    assert not of.is_price_overlaid

    def test_capacity_overlay_and_weight(self):
        clock = FakeClock()
        store = ObjectStore(clock)
        inner = KwokCloudProvider(store, catalog=instance_types(4))
        cloud = OverlayCloudProvider(inner, store)
        heavy = NodeOverlay(requirements=[], weight=10, price="5.0")
        heavy.metadata.name = "heavy"
        light = NodeOverlay(requirements=[], weight=1, price="9.0")
        light.metadata.name = "light"
        cap = NodeOverlay(requirements=[], capacity={"example.com/gpu": 4.0})
        cap.metadata.name = "gpus"
        for o in (heavy, light, cap):
            store.create(ObjectStore.NODE_OVERLAYS, o)
        pool = NodePool()
        its = cloud.get_instance_types(pool)
        for it in its:
            assert all(of.price == 5.0 for of in it.offerings)  # heaviest wins
            assert it.capacity["example.com/gpu"] == 4.0
            assert it.is_capacity_overlay_applied


class TestObservability:
    """Round-2 observability surface: SPI metrics decorator, per-object
    state gauges, status-condition auto-metrics, queue families, logging."""

    def _env(self):
        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.cloudprovider.metrics import MetricsCloudProvider
        from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
        from karpenter_tpu.models.nodepool import NodePool
        from karpenter_tpu.models.pod import make_pod
        from karpenter_tpu.state.store import ObjectStore
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        store = ObjectStore(clock)
        cloud = MetricsCloudProvider(KwokCloudProvider(store, catalog=instance_types(16)))
        mgr = Manager(store, cloud, clock)
        store.create(ObjectStore.NODEPOOLS, NodePool())
        store.create(ObjectStore.PODS, make_pod("p", cpu=0.5))
        mgr.run_until_idle()
        cloud.unwrapped.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        mgr.run_until_idle()
        return clock, store, cloud, mgr

    def test_spi_decorator_measures_calls(self):
        from karpenter_tpu.utils import metrics

        before = metrics.CLOUDPROVIDER_DURATION.totals.get(
            ("", "create", "kwok"), 0
        )
        clock, store, cloud, mgr = self._env()
        assert (
            metrics.CLOUDPROVIDER_DURATION.totals.get(("", "create", "kwok"), 0)
            > before
        )
        assert cloud.name == "kwok"

    def test_spi_decorator_counts_errors(self):
        from karpenter_tpu.cloudprovider import errors
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_tpu.cloudprovider.metrics import MetricsCloudProvider
        from karpenter_tpu.models.nodeclaim import NodeClaim
        from karpenter_tpu.utils import metrics

        fake = FakeCloudProvider()
        fake.next_create_err = errors.InsufficientCapacityError("no capacity")
        wrapped = MetricsCloudProvider(fake)
        before = metrics.CLOUDPROVIDER_ERRORS.get(
            method="create", provider="fake", error="InsufficientCapacityError"
        )
        try:
            wrapped.create(NodeClaim())
        except errors.InsufficientCapacityError:
            pass
        assert (
            metrics.CLOUDPROVIDER_ERRORS.get(
                method="create", provider="fake", error="InsufficientCapacityError"
            )
            == before + 1
        )

    def test_state_gauges_populated(self):
        from karpenter_tpu.utils import metrics

        clock, store, cloud, mgr = self._env()
        mgr.run_maintenance()
        node = store.nodes()[0]
        assert metrics.NODE_ALLOCATABLE.get(
            node_name=node.name, nodepool="default", resource_type="cpu"
        ) > 0
        assert metrics.NODE_TOTAL_POD_REQUESTS.get(
            node_name=node.name, nodepool="default", resource_type="cpu"
        ) >= 0.5
        util = metrics.NODE_UTILIZATION.get(
            node_name=node.name, nodepool="default", resource_type="cpu"
        )
        assert 0.0 < util <= 100.0
        assert metrics.POD_STATE.get(
            name="p", namespace="default", node=node.name, nodepool="default",
            phase="Pending", scheduled="true",
        ) == 1.0 or any(
            k for k in metrics.POD_STATE.values if k[0] == "p"
        )
        assert metrics.POD_BOUND_DURATION.totals[()] >= 1
        # status-condition gauges cover claim conditions
        assert metrics.STATUS_CONDITION_COUNT.get(
            kind="NodeClaim", type="Launched", status="True"
        ) >= 1.0

    def test_scheduler_queue_metrics(self):
        from karpenter_tpu.utils import metrics

        clock, store, cloud, mgr = self._env()
        # queue drained after a successful pass
        assert metrics.SCHEDULER_QUEUE_DEPTH.get() >= 1.0
        assert metrics.PENDING_PODS_BY_ZONE.get(zone="any") >= 1.0

    def test_condition_transitions_counted(self):
        from karpenter_tpu.models.objects import ConditionSet
        from karpenter_tpu.utils import metrics

        before = metrics.STATUS_CONDITION_TRANSITIONS.get(type="TestCond", status="True")
        cs = ConditionSet()
        cs.set_true("TestCond")
        cs.set_true("TestCond")  # no transition
        cs.set_false("TestCond")
        assert metrics.STATUS_CONDITION_TRANSITIONS.get(type="TestCond", status="True") == before + 1
        assert metrics.STATUS_CONDITION_TRANSITIONS.get(type="TestCond", status="False") >= 1

    def test_logger_and_change_monitor(self):
        import io
        import json as _json

        from karpenter_tpu.utils.clock import FakeClock
        from karpenter_tpu.utils.logging import ChangeMonitor, Logger

        buf = io.StringIO()
        log = Logger(level="info", stream=buf).with_values(controller="provisioner")
        log.debug("hidden")
        log.info("solved", pods=5)
        lines = [l for l in buf.getvalue().splitlines() if l]
        assert len(lines) == 1
        rec = _json.loads(lines[0])
        assert rec["message"] == "solved" and rec["controller"] == "provisioner"
        assert Logger.nop() is not None  # nop never raises
        Logger.nop().error("dropped")

        clock = FakeClock()
        cm = ChangeMonitor(ttl_seconds=60.0, clock=clock)
        assert cm.has_changed("k", {"a": 1})
        assert not cm.has_changed("k", {"a": 1})
        assert cm.has_changed("k", {"a": 2})
        clock.step(61.0)
        assert cm.has_changed("k", {"a": 2})  # TTL re-log
