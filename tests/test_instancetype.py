"""InstanceType/Offering semantics (behavioral parity with reference
pkg/cloudprovider/types.go)."""

from karpenter_tpu.cloudprovider import (
    InstanceType,
    InstanceTypeOverhead,
    Offering,
    order_by_price,
    satisfies_min_values,
    truncate_instance_types,
    worst_launch_price,
)
from karpenter_tpu.cloudprovider.fake import (
    FakeCloudProvider,
    instance_types,
    new_instance_type,
)
from karpenter_tpu.cloudprovider.instancetype import adjusted_price
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodeclaim import NodeClaim, NodeClaimSpec
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.scheduling import Operator, Requirement, Requirements
from karpenter_tpu.utils import resources as res
import pytest


def make_it(name="it-1", price=1.0, zone="z1", ct=l.CAPACITY_TYPE_ON_DEMAND, cpu=4.0, **kw):
    return InstanceType(
        name=name,
        requirements=Requirements(
            Requirement.new(l.LABEL_INSTANCE_TYPE, Operator.IN, name),
        ),
        offerings=[
            Offering(
                requirements=Requirements(
                    Requirement.new(l.LABEL_TOPOLOGY_ZONE, Operator.IN, zone),
                    Requirement.new(l.CAPACITY_TYPE_LABEL_KEY, Operator.IN, ct),
                ),
                price=price,
            )
        ],
        capacity={res.CPU: cpu, res.MEMORY: 8 * 2**30, res.PODS: 110.0},
        **kw,
    )


class TestAllocatable:
    def test_overhead_subtracted(self):
        it = make_it(
            overhead=InstanceTypeOverhead(
                kube_reserved={res.CPU: 0.1},
                system_reserved={res.CPU: 0.1},
                eviction_threshold={res.MEMORY: 100.0},
            )
        )
        alloc = it.allocatable()
        assert alloc[res.CPU] == pytest.approx(3.8)
        assert alloc[res.MEMORY] == pytest.approx(8 * 2**30 - 100.0)

    def test_hugepages_reduce_allocatable_memory(self):
        it = InstanceType(
            "huge",
            Requirements(),
            [],
            {res.CPU: 4.0, res.MEMORY: 8 * 2**30, "hugepages-2Mi": 2 * 2**30},
        )
        assert it.allocatable()[res.MEMORY] == pytest.approx(6 * 2**30)

    def test_hugepages_cannot_go_negative(self):
        it = InstanceType(
            "huge", Requirements(), [], {res.MEMORY: 2**30, "hugepages-1Gi": 2 * 2**30}
        )
        assert it.allocatable()[res.MEMORY] == 0.0

    def test_offering_override_groups(self):
        base_off = Offering(requirements=Requirements(), price=1.0)
        override_off = Offering(
            requirements=Requirements(), price=2.0, capacity_override={res.CPU: 8.0}
        )
        it = InstanceType("o", Requirements(), [base_off, override_off], {res.CPU: 4.0})
        groups = it.allocatable_offerings()
        assert len(groups) == 2
        assert groups[0].allocatable[res.CPU] == 4.0  # base first
        assert groups[1].allocatable[res.CPU] == 8.0
        assert groups[0].offerings == [base_off]
        assert groups[1].offerings == [override_off]

    def test_unavailable_offerings_excluded(self):
        off = Offering(requirements=Requirements(), price=1.0, available=False)
        it = InstanceType("u", Requirements(), [off], {res.CPU: 4.0})
        assert it.allocatable_offerings()[0].offerings == []


class TestOrdering:
    def test_order_by_price_cheapest_compatible(self):
        a, b = make_it("a", price=3.0), make_it("b", price=1.0)
        assert [it.name for it in order_by_price([a, b], Requirements())] == ["b", "a"]

    def test_incompatible_offerings_ignored_in_ordering(self):
        a = make_it("a", price=1.0, zone="z-unwanted")
        b = make_it("b", price=5.0, zone="z1")
        reqs = Requirements(Requirement.new(l.LABEL_TOPOLOGY_ZONE, Operator.IN, "z1"))
        assert [it.name for it in order_by_price([a, b], reqs)] == ["b", "a"]


class TestMinValues:
    def _reqs(self, mv_type=3, mv_family=3):
        return Requirements(
            Requirement.new(l.LABEL_INSTANCE_TYPE, Operator.EXISTS, min_values=mv_type),
            Requirement.new("family", Operator.EXISTS, min_values=mv_family),
        )

    def _it(self, name, family):
        it = make_it(name)
        it.requirements.add(Requirement.new("family", Operator.IN, family))
        return it

    def test_satisfied(self):
        its = [self._it("c4.large", "c4"), self._it("c5.xlarge", "c5"), self._it("m4.2xlarge", "m4")]
        n, bad, err = satisfies_min_values(its, self._reqs())
        assert (n, bad, err) == (3, {}, None)

    def test_unsatisfied_family(self):
        its = [self._it("c4.large", "c4"), self._it("c4.xlarge", "c4"), self._it("c5.2xlarge", "c5")]
        n, bad, err = satisfies_min_values(its, self._reqs())
        assert n == 3 and bad == {"family": 2} and err is not None

    def test_no_min_values_short_circuits(self):
        assert satisfies_min_values([], Requirements()) == (0, {}, None)

    def test_truncate_raises_when_minvalues_broken(self):
        its = [self._it(f"c4-{i}", "c4") for i in range(5)]
        with pytest.raises(ValueError):
            truncate_instance_types(its, self._reqs(mv_type=3, mv_family=2), max_items=4)

    def test_truncate_best_effort_allows(self):
        its = [self._it(f"c4-{i}", "c4") for i in range(5)]
        out = truncate_instance_types(
            its, self._reqs(mv_type=3, mv_family=2), max_items=4, min_values_policy_best_effort=True
        )
        assert len(out) == 4


class TestOfferings:
    def test_worst_launch_price_precedence(self):
        mk = lambda ct, price: Offering(
            requirements=Requirements(
                Requirement.new(l.CAPACITY_TYPE_LABEL_KEY, Operator.IN, ct),
                Requirement.new(l.LABEL_TOPOLOGY_ZONE, Operator.IN, "z1"),
            ),
            price=price,
        )
        offs = [mk(l.CAPACITY_TYPE_ON_DEMAND, 10.0), mk(l.CAPACITY_TYPE_SPOT, 3.0), mk(l.CAPACITY_TYPE_SPOT, 4.0)]
        # spot present -> worst spot price wins over on-demand
        assert worst_launch_price(offs, Requirements()) == 4.0
        # restrict to on-demand
        od = Requirements(Requirement.new(l.CAPACITY_TYPE_LABEL_KEY, Operator.IN, l.CAPACITY_TYPE_ON_DEMAND))
        assert worst_launch_price(offs, od) == 10.0

    def test_adjusted_price(self):
        assert adjusted_price(10.0, "") == 10.0
        assert adjusted_price(10.0, "5.5") == 5.5
        assert adjusted_price(10.0, "+2") == 12.0
        assert adjusted_price(10.0, "-2") == 8.0
        assert adjusted_price(10.0, "+50%") == 15.0
        assert adjusted_price(10.0, "-150%") == 0.0  # floors at zero


class TestFakeProvider:
    def test_create_resolves_cheapest_offering(self):
        cp = FakeCloudProvider()
        claim = NodeClaim(spec=NodeClaimSpec(requirements=[]))
        resolved = cp.create(claim)
        assert resolved.status.provider_id
        assert resolved.metadata.labels[l.CAPACITY_TYPE_LABEL_KEY] == l.CAPACITY_TYPE_SPOT
        assert resolved.status.allocatable[res.CPU] > 0

    def test_create_respects_requirements(self):
        cp = FakeCloudProvider()
        claim = NodeClaim(
            spec=NodeClaimSpec(
                requirements=[
                    {"key": l.CAPACITY_TYPE_LABEL_KEY, "operator": "In", "values": [l.CAPACITY_TYPE_ON_DEMAND]},
                    {"key": l.LABEL_TOPOLOGY_ZONE, "operator": "In", "values": ["test-zone-2"]},
                ]
            )
        )
        resolved = cp.create(claim)
        assert resolved.metadata.labels[l.CAPACITY_TYPE_LABEL_KEY] == l.CAPACITY_TYPE_ON_DEMAND
        assert resolved.metadata.labels[l.LABEL_TOPOLOGY_ZONE] == "test-zone-2"

    def test_insufficient_capacity(self):
        from karpenter_tpu.cloudprovider import InsufficientCapacityError

        cp = FakeCloudProvider(catalog=[])
        with pytest.raises(InsufficientCapacityError):
            cp.create(NodeClaim())

    def test_delete_then_not_found(self):
        from karpenter_tpu.cloudprovider import NodeClaimNotFoundError

        cp = FakeCloudProvider()
        claim = cp.create(NodeClaim())
        cp.delete(claim)
        with pytest.raises(NodeClaimNotFoundError):
            cp.delete(claim)

    def test_generator_shapes(self):
        its = instance_types(400)
        assert len(its) == 400
        assert len({it.name for it in its}) == 400  # unique names
        # spot is 70% of on-demand for every type
        for it in its[:10]:
            od = it.offering_price("test-zone-1", l.CAPACITY_TYPE_ON_DEMAND)
            spot = it.offering_price("test-zone-1", l.CAPACITY_TYPE_SPOT)
            assert spot == pytest.approx(od * 0.7, rel=1e-3)

    def test_scripted_error(self):
        from karpenter_tpu.cloudprovider import CreateError

        cp = FakeCloudProvider()
        cp.next_create_err = CreateError("boom", reason="Scripted")
        with pytest.raises(CreateError):
            cp.create(NodeClaim())
        cp.create(NodeClaim())  # next call succeeds
