"""Termination-grace-period drain, StaticDrift replace-then-delete, and
upgrade hydration.

Mirrors reference terminator.go:140-176 (DeleteExpiringPods: blocked pods
preemptively deleted at node-expiry minus pod TGP, grace clamped to the
node's remaining life), termination/controller.go:244-258 (grace elapsed
stops all waiting), disruption/staticdrift.go (replacement before delete,
never below replicas), and nodeclaim/hydration (nodeclass label backfill).
"""

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
from karpenter_tpu.controllers.node_termination import TERMINATION_TS_ANNOTATION
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import Budget, NodePool
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import FakeClock


def build_env(catalog_size=50):
    clock = FakeClock()
    store = ObjectStore(clock)
    cloud = KwokCloudProvider(store, catalog=instance_types(catalog_size))
    mgr = Manager(store, cloud, clock)
    return clock, store, cloud, mgr


def provision_bound_pod(store, cloud, mgr, pod):
    store.create(ObjectStore.PODS, pod)
    mgr.run_until_idle()
    cloud.simulate_kubelet_ready()
    mgr.run_until_idle()
    KubeSchedulerSim(store, mgr.cluster).bind_pending()
    mgr.run_until_idle()
    assert pod.spec.node_name


class TestTGPDrain:
    def _env_with_blocked_pod(self, claim_tgp, pod_tgp):
        clock, store, cloud, mgr = build_env()
        pool = NodePool()
        pool.metadata.name = "default"
        pool.spec.template.spec.termination_grace_period_seconds = claim_tgp
        store.create(ObjectStore.NODEPOOLS, pool)
        pod = make_pod("stubborn", cpu=0.5)
        pod.metadata.annotations[l.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        pod.spec.termination_grace_period_seconds = pod_tgp
        provision_bound_pod(store, cloud, mgr, pod)
        return clock, store, cloud, mgr, pod

    def test_blocked_pod_deleted_at_expiry_minus_tgp(self):
        """The do-not-disrupt pod survives the initial drain, then is
        preemptively deleted exactly when node-expiry - pod TGP passes,
        with the delete's grace clamped to the node's remaining life."""
        clock, store, cloud, mgr, pod = self._env_with_blocked_pod(
            claim_tgp=300.0, pod_tgp=120.0
        )
        claim = store.nodeclaims()[0]
        assert claim.spec.termination_grace_period_seconds == 300.0
        store.delete(ObjectStore.NODECLAIMS, claim.name)
        mgr.run_until_idle()
        # drain started: termination time stamped, pod still bound
        claim = store.get(ObjectStore.NODECLAIMS, claim.name)
        assert claim is not None, "claim finalized despite blocked pod"
        stamped = float(claim.metadata.annotations[TERMINATION_TS_ANNOTATION])
        assert stamped == clock.now() + 300.0
        pod = store.get(ObjectStore.PODS, "stubborn")
        assert pod.spec.node_name, "blocked pod was evicted before its window"

        # just before T - pod_tgp: still bound
        clock.step(300.0 - 120.0 - 1.0)
        mgr.run_maintenance()
        pod = store.get(ObjectStore.PODS, "stubborn")
        assert pod.spec.node_name

        # past T - pod_tgp: deleted with grace clamped to remaining life
        clock.step(2.0)
        before = clock.now()
        mgr.run_maintenance()
        pod = store.get(ObjectStore.PODS, "stubborn")
        assert not pod.spec.node_name, "pod not preemptively deleted"
        grace = float(pod.metadata.annotations[l.GROUP + "/preemptive-delete-grace-seconds"])
        # recorded when the drain ran; the maintenance pass may advance the
        # fake clock a batch window past `before`
        assert stamped - before - 2.0 <= grace <= stamped - before
        assert grace <= 120.0
        # with the node drained, finalization completes
        mgr.run_maintenance()
        assert store.get(ObjectStore.NODECLAIMS, claim.name) is None

    def test_pod_tgp_longer_than_claim_tgp_deletes_immediately(self):
        """pod TGP > claim TGP: the delete window opened before the drain
        began, so the pod goes immediately with grace = full node life."""
        clock, store, cloud, mgr, pod = self._env_with_blocked_pod(
            claim_tgp=300.0, pod_tgp=600.0
        )
        claim = store.nodeclaims()[0]
        store.delete(ObjectStore.NODECLAIMS, claim.name)
        mgr.run_until_idle()
        pod = store.get(ObjectStore.PODS, "stubborn")
        assert not pod.spec.node_name
        grace = float(pod.metadata.annotations[l.GROUP + "/preemptive-delete-grace-seconds"])
        assert abs(grace - 300.0) < 1e-6

    def test_no_tgp_blocks_forever(self):
        """Without a claim TGP the drain never forces the blocked pod and
        the instance keeps running (reference retries indefinitely)."""
        clock, store, cloud, mgr, pod = self._env_with_blocked_pod(
            claim_tgp=None, pod_tgp=30.0
        )
        claim = store.nodeclaims()[0]
        store.delete(ObjectStore.NODECLAIMS, claim.name)
        mgr.run_until_idle()
        clock.step(7200.0)
        mgr.run_maintenance()
        pod = store.get(ObjectStore.PODS, "stubborn")
        assert pod.spec.node_name
        assert store.get(ObjectStore.NODECLAIMS, claim.name) is not None

    def test_grace_elapsed_forces_finalization(self):
        """Past the node termination time the controller stops waiting even
        if something is still blocking (controller.go:244-258)."""
        clock, store, cloud, mgr, pod = self._env_with_blocked_pod(
            claim_tgp=300.0, pod_tgp=1.0
        )
        claim = store.nodeclaims()[0]
        store.delete(ObjectStore.NODECLAIMS, claim.name)
        mgr.run_until_idle()
        clock.step(301.0)
        mgr.run_maintenance()
        assert store.get(ObjectStore.NODECLAIMS, claim.name) is None

    def test_unblocked_pods_drain_instantly(self):
        clock, store, cloud, mgr = build_env()
        store.create(ObjectStore.NODEPOOLS, NodePool())
        pod = make_pod("plain", cpu=0.5)
        provision_bound_pod(store, cloud, mgr, pod)
        claim = store.nodeclaims()[0]
        store.delete(ObjectStore.NODECLAIMS, claim.name)
        mgr.run_until_idle()
        assert store.get(ObjectStore.NODECLAIMS, claim.name) is None
        pod = store.get(ObjectStore.PODS, "plain")
        assert not pod.spec.node_name


class TestStaticDrift:
    def _static_env(self, replicas=2):
        clock, store, cloud, mgr = build_env()
        pool = NodePool()
        pool.metadata.name = "static"
        pool.spec.replicas = replicas
        pool.spec.disruption.budgets = [Budget(nodes="100%")]
        store.create(ObjectStore.NODEPOOLS, pool)
        mgr.run_maintenance()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        assert len(store.nodes()) == replicas
        return clock, store, cloud, mgr, pool

    def test_replace_then_delete_never_below_replicas(self):
        clock, store, cloud, mgr, pool = self._static_env(replicas=2)
        # operator changes the template -> hash drift on both claims
        pool.spec.template.labels["team"] = "new"
        store.update(ObjectStore.NODEPOOLS, pool)
        assert mgr.mark_drift() >= 1
        drifted = [
            c.name for c in store.nodeclaims() if c.conditions.is_true("Drifted")
        ]
        assert len(drifted) == 2

        min_live = 2
        for _ in range(12):
            clock.step(20.0)
            mgr.run_disruption_once()
            cloud.simulate_kubelet_ready()
            mgr.run_until_idle()
            live = [c for c in store.nodeclaims() if not c.metadata.deleting]
            min_live = min(min_live, len(live))
            mgr.run_maintenance()
        # the drift cycle replaced every drifted claim without ever
        # dropping below the pool's replica count
        assert min_live >= 2, f"static pool dipped to {min_live} live claims"
        live = [c for c in store.nodeclaims() if not c.metadata.deleting]
        assert len(live) == 2
        assert not any(c.name in drifted for c in live), "drifted claims survived"
        # replacements carry the new template hash (no re-drift loop)
        mgr.mark_drift()
        assert not any(c.conditions.is_true("Drifted") for c in store.nodeclaims())

    def test_static_pools_skip_normal_disruption(self):
        """Emptiness/consolidation never touch static nodes even when idle
        long past consolidateAfter (consolidation.go:102, emptiness.go:43)."""
        clock, store, cloud, mgr, pool = self._static_env(replicas=1)
        clock.step(3600.0)
        for _ in range(3):
            cmd = mgr.run_disruption_once()
            assert cmd is None
            clock.step(20.0)
        assert len([c for c in store.nodeclaims() if not c.metadata.deleting]) == 1


class TestHydration:
    def test_nodeclass_label_backfilled(self):
        clock, store, cloud, mgr = build_env()
        store.create(ObjectStore.NODEPOOLS, NodePool())
        pod = make_pod("p", cpu=0.5)
        provision_bound_pod(store, cloud, mgr, pod)
        claim = store.nodeclaims()[0]
        # simulate a pre-upgrade object: ref present, label absent
        claim.spec.node_class_ref = {"group": "karpenter.kwok.sh", "kind": "KWOKNodeClass", "name": "default"}
        claim.metadata.labels.pop("karpenter.kwok.sh/kwoknodeclass", None)
        store.update(ObjectStore.NODECLAIMS, claim)
        out = mgr.run_maintenance()
        assert out["hydrated"] >= 1
        claim = store.get(ObjectStore.NODECLAIMS, claim.name)
        assert claim.metadata.labels["karpenter.kwok.sh/kwoknodeclass"] == "default"
        node = store.node_by_provider_id(claim.status.provider_id)
        assert node.metadata.labels["karpenter.kwok.sh/kwoknodeclass"] == "default"
        # idempotent: second pass is a no-op
        assert mgr.run_maintenance()["hydrated"] == 0
