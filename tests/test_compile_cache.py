"""Persistent compilation cache: a process restart must not pay the cold
XLA compile again (VERDICT r3 #8 — a cold compile after restart would blow
most of the reference's 1m Solve window, provisioner.go:415).

Two fresh subprocesses solve the identical problem against a shared cache
dir: the first populates it, the second must hit it (observed via JAX's
cache-hit monitoring event) without writing new entries — which also pins
that the bucketed shape classes (pow2 pod/claim/vocab pads) produce
deterministic cache keys."""

import json
import os
import subprocess
import sys

import pytest

_CHILD = r"""
import json, os, time
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
from karpenter_tpu.utils.accel import force_cpu
force_cpu()
from jax._src import monitoring

hits = [0]

def _on_event(event, **kw):
    if event == "/jax/compilation_cache/cache_hits":
        hits[0] += 1

monitoring.register_event_listener(_on_event)

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import make_pod

pool = NodePool(); pool.metadata.name = "default"
templates = build_templates([(pool, instance_types(16))])
pods = [make_pod(f"p-{i}", cpu=0.5) for i in range(48)]
sched = TPUScheduler(templates)
t0 = time.perf_counter()
result = sched.solve(pods)
cold_s = time.perf_counter() - t0
assert not result.unschedulable
# the warm solve re-sizes the claims axis AND the active window (window
# W is part of the compiled shapes, hence of the cache keys) — run it in
# BOTH children so the windowed executables land in the cache too and
# the key-stability assertion covers them
warm = sched.solve(pods)
assert not warm.unschedulable
assert len(warm.claims) == len(result.claims)
scan = sched.last_timings.get("scan") or {}
# a gang solve exercises the gang-atomic kernel's encode columns and
# slice-shape tables (ISSUE-6): its executables must land in the cache
# with deterministic keys too, so BOTH children run one
from karpenter_tpu.gang import make_gang_pods
gang_pods = make_gang_pods("cc-gang", 4, cpu=1.5) + pods[:8]
gres = sched.solve(gang_pods)
assert not gres.unschedulable
gang_claims = sum(1 for c in gres.claims if c.gang)
assert gang_claims >= 1, "the gang solve never opened a slice claim"
# a warm resident delta round (ISSUE 7): the session's append path
# compiles its own executables (fill dispatch at the delta shapes, the
# gather preps, retract_tail) — run it in BOTH children so cache-key
# stability covers the resident/incremental path too
session = sched.resident_session()
sres = session.solve(list(pods))
assert session._r is not None, "session did not go resident"
delta = [make_pod(f"rd-{i}", cpu=0.5) for i in range(8)]
dres = session.solve(pods + delta)
assert session.last_mode == "delta", session.last_reason
assert not dres.unschedulable
# the delta round ran under KTPU_GUARD_AUDIT_RATE=1.0 (ISSUE 10): the
# shadow audit's cold-twin solve compiled through the SAME cache, and
# it must agree with the delta result bit-exactly
audit = session.last_timings["resident"]["audit"]
assert audit is not None and audit["verdict"] == "pass", audit
rres = session.solve(list(pods))  # retract the delta batch
assert session.last_mode == "delta", session.last_reason
assert len(rres.claims) == len(sres.claims)
print(json.dumps({
    "cold_s": cold_s,
    "cache_hits": hits[0],
    "claims": len(result.claims),
    "gang_claims": gang_claims,
    "delta_claims": len(dres.claims),
    "audit_verdict": audit["verdict"],
    "window": scan.get("window"),
}))
"""


def _run_child(cache_dir: str) -> dict:
    env = dict(os.environ)
    env["KTPU_COMPILE_CACHE"] = cache_dir
    # pin the active window so both children compile the SAME windowed
    # executables (cache keys include W via the carry shapes); without the
    # pin, determinism would hinge on the adaptive sizing heuristics
    env["KTPU_SCAN_WINDOW"] = "32"
    # force the shadow audit on (ISSUE 10): the child's delta round is
    # audited against its cold twin, so guardrail executables join the
    # cache-key stability contract
    env["KTPU_GUARD_AUDIT_RATE"] = "1.0"
    out = subprocess.run(
        [sys.executable, "-c", _CHILD],
        capture_output=True,
        text=True,
        env=env,
        timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def _cache_entries(cache_dir: str) -> int:
    return sum(len(files) for _, _, files in os.walk(cache_dir))


def test_restart_skips_cold_compile(tmp_path):
    cache_dir = str(tmp_path / "xla_cache")
    first = _run_child(cache_dir)
    populated = _cache_entries(cache_dir)
    # the zero-entry skip below must not mask a broken wiring: even when
    # XLA declines to PERSIST entries, enabling the cache must at least
    # have created the directory — if it doesn't exist, KTPU_COMPILE_CACHE
    # never reached jax.config and that IS a regression, not a platform
    # limitation (ISSUE-4 satellite; flake first noted in PR 2)
    assert os.path.isdir(cache_dir), (
        f"KTPU_COMPILE_CACHE={cache_dir} was never initialized: the cache "
        "directory does not exist, so the env wiring is broken (this is "
        "NOT the benign zero-entry platform case)"
    )
    if populated == 0:
        # pre-existing environment limitation, not a regression: on some
        # CPU-only platforms XLA declines to persist entries (compiles
        # below the cache's min-entry-size / unsupported backend), so
        # there is nothing for the second run to hit. Keep the hard
        # assert wherever entries ARE written (any accelerator, and CPU
        # builds that do persist). The reason is logged with the solve
        # diagnostics so CI history shows WHY each skip happened.
        reason = (
            "XLA persistent compile cache wrote zero entries on this "
            f"platform (cache dir created, cold_s={first['cold_s']:.1f}, "
            f"claims={first['claims']}); restart warm-start is "
            "unobservable here"
        )
        print(f"SKIP[test_restart_skips_cold_compile]: {reason}")
        pytest.skip(reason)

    second = _run_child(cache_dir)
    after = _cache_entries(cache_dir)
    assert second["claims"] == first["claims"]
    assert second["gang_claims"] == first["gang_claims"]
    assert second["delta_claims"] == first["delta_claims"]
    assert second["window"] == first["window"], (
        "the pinned scan window must size identically across restarts "
        f"({first['window']} vs {second['window']})"
    )
    # deterministic shape-bucketed keys (claims axis, pads AND window W):
    # the rerun adds nothing new
    assert after == populated, f"cache grew {populated} -> {after}; keys unstable"
    # and the compiles were served from disk
    assert second["cache_hits"] > 0, "no persistent-cache hits on restart"
