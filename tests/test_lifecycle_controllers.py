"""Termination/drain, garbage collection, expiration, and node repair."""

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.cloudprovider.spi import RepairPolicy
from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import FakeClock


def build_env(expire_after=None, catalog_size=50):
    clock = FakeClock()
    store = ObjectStore(clock)
    cloud = KwokCloudProvider(store, catalog=instance_types(catalog_size))
    mgr = Manager(store, cloud, clock)
    pool = NodePool()
    pool.metadata.name = "default"
    pool.spec.template.spec.expire_after_seconds = expire_after
    store.create(ObjectStore.NODEPOOLS, pool)
    return clock, store, cloud, mgr


def provision(mgr, store, cloud, pods):
    for p in pods:
        store.create(ObjectStore.PODS, p)
    mgr.run_until_idle()
    cloud.simulate_kubelet_ready()
    mgr.run_until_idle()
    KubeSchedulerSim(store, mgr.cluster).bind_pending()
    mgr.run_until_idle()


class TestInitialization:
    def test_known_ephemeral_taint_blocks_initialization(self):
        """A node still carrying node.kubernetes.io/not-ready must not be
        marked Initialized even if Ready and startup taints are clear
        (initialization.go:78-81 KnownEphemeralTaintsRemoved)."""
        from karpenter_tpu.models.nodeclaim import COND_INITIALIZED
        from karpenter_tpu.models.taints import NO_SCHEDULE, TAINT_NODE_NOT_READY, Taint

        clock, store, cloud, mgr = build_env()
        store.create(ObjectStore.PODS, make_pod("p", cpu=0.5))
        mgr.run_until_idle()  # claim created, node joined + registered
        node = store.nodes()[0]
        node.spec.taints.append(Taint(key=TAINT_NODE_NOT_READY, effect=NO_SCHEDULE))
        store.update(ObjectStore.NODES, node)
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        claim = store.nodeclaims()[0]
        assert not claim.conditions.is_true(COND_INITIALIZED)
        node = store.nodes()[0]
        node.spec.taints = [t for t in node.spec.taints if t.key != TAINT_NODE_NOT_READY]
        store.update(ObjectStore.NODES, node)
        mgr.run_until_idle()
        claim = store.nodeclaims()[0]
        assert claim.conditions.is_true(COND_INITIALIZED)


class TestTerminationDrain:
    def test_claim_deletion_evicts_and_reschedules_pods(self):
        """The earlier gap: deleting a claim must drain its pods back to
        Pending so the provisioner re-places them."""
        clock, store, cloud, mgr = build_env()
        provision(mgr, store, cloud, [make_pod(f"p-{i}", cpu=0.5) for i in range(6)])
        assert all(p.spec.node_name for p in store.pods())
        claim = store.nodeclaims()[0]
        n_pods_on_node = sum(
            1 for p in store.pods() if p.spec.node_name == claim.status.node_name
        )
        assert n_pods_on_node > 0
        store.delete(ObjectStore.NODECLAIMS, claim.name)
        mgr.run_until_idle()
        # evicted pods become provisionable and a replacement claim appears
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        orphans = [
            p
            for p in store.pods()
            if p.spec.node_name
            and not any(n.name == p.spec.node_name for n in store.nodes())
        ]
        assert orphans == []
        assert all(p.spec.node_name for p in store.pods()), "pods not rescheduled"

    def test_drain_priority_order(self):
        from karpenter_tpu.controllers.node_termination import Terminator

        clock, store, cloud, mgr = build_env()
        critical = make_pod("critical", cpu=0.1)
        critical.spec.priority = 2_000_000_001
        normal = make_pod("normal", cpu=0.1)
        provision(mgr, store, cloud, [critical, normal])
        node = store.nodes()[0]
        order = []
        t = Terminator(store, clock)
        orig = t._evict
        t._evict = lambda p: (order.append(p.name), orig(p))
        t.drain(node)
        assert order == ["normal", "critical"]


class TestGarbageCollection:
    def test_vanished_instance_collects_claim(self):
        clock, store, cloud, mgr = build_env()
        provision(mgr, store, cloud, [make_pod("p", cpu=0.5)])
        claim = store.nodeclaims()[0]
        # the instance disappears behind karpenter's back
        node = store.nodes()[0]
        cloud_node = node
        del_claim = claim
        # simulate cloud-side vanish: remove from provider accounting only
        cloud.delete(claim)
        out = mgr.run_maintenance()
        assert out["garbage_collected"] >= 1
        assert store.get(ObjectStore.NODECLAIMS, del_claim.name) is None
        # the pod on the vanished node was evicted and re-provisions
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        pod = store.get(ObjectStore.PODS, "p")
        assert pod.spec.node_name and any(
            n.name == pod.spec.node_name for n in store.nodes()
        ), "pod stranded after instance vanished"

    def test_health_flap_does_not_repair(self):
        from karpenter_tpu.cloudprovider.spi import RepairPolicy

        clock, store, cloud, mgr = build_env()
        provision(mgr, store, cloud, [make_pod("p", cpu=0.5)])
        cloud.repair_policies = lambda: [
            RepairPolicy(condition_type="Ready", condition_status="False", toleration_seconds=300.0)
        ]
        node = store.nodes()[0]
        mgr.health.observe(node.name, "Ready", "False")
        clock.step(10.0)
        mgr.health.resolve(node.name, "Ready")  # the blip recovered
        clock.step(600.0)
        assert mgr.run_maintenance()["repaired"] == 0

    def test_orphan_node_collected(self):
        clock, store, cloud, mgr = build_env()
        provision(mgr, store, cloud, [make_pod("p", cpu=0.5)])
        node = store.nodes()[0]
        claim = store.nodeclaims()[0]
        # claim vanishes without finalization (e.g. etcd surgery)
        claim.metadata.finalizers = []
        store.delete(ObjectStore.NODECLAIMS, claim.name)
        # instance still exists; the node is managed but claimless
        out = mgr.run_maintenance()
        assert all(n.name != node.name for n in store.nodes())


class TestExpiration:
    def test_expired_claim_replaced(self):
        clock, store, cloud, mgr = build_env(expire_after=3600.0)
        provision(mgr, store, cloud, [make_pod("p", cpu=0.5)])
        name = store.nodeclaims()[0].name
        clock.step(3601.0)
        out = mgr.run_maintenance()
        assert out["expired"] == 1
        assert store.get(ObjectStore.NODECLAIMS, name) is None
        # the drained pod reschedules onto a fresh claim
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        assert all(p.spec.node_name for p in store.pods())

    def test_not_expired_yet(self):
        clock, store, cloud, mgr = build_env(expire_after=3600.0)
        provision(mgr, store, cloud, [make_pod("p", cpu=0.5)])
        clock.step(600.0)
        assert mgr.run_maintenance()["expired"] == 0


class TestStatusControllers:
    def test_consistency_flags_capacity_mismatch(self):
        clock, store, cloud, mgr = build_env()
        provision(mgr, store, cloud, [make_pod("p", cpu=0.5)])
        out = mgr.run_maintenance()
        assert out["inconsistent"] == 0
        claim = store.nodeclaims()[0]
        assert claim.conditions.is_true("ConsistentStateFound")
        node = store.nodes()[0]
        node.status.capacity["cpu"] = node.status.capacity["cpu"] * 2  # cloud lied
        out = mgr.run_maintenance()
        assert out["inconsistent"] == 1
        assert not store.nodeclaims()[0].conditions.is_true("ConsistentStateFound")

    def test_nodepool_status_updated(self):
        clock, store, cloud, mgr = build_env()
        provision(mgr, store, cloud, [make_pod("p", cpu=0.5)])
        mgr.run_maintenance()
        pool = store.get(ObjectStore.NODEPOOLS, "default")
        assert pool.status.node_count == 1
        assert pool.status.resources.get("cpu", 0) > 0
        assert pool.conditions.is_true("Ready")
        assert pool.metadata.annotations.get(
            "karpenter.sh/nodepool-hash"
        ) == pool.static_hash()


class TestNodeRepair:
    def _policies(self, cloud):
        cloud._repair_policies = [
            RepairPolicy(
                condition_type="Ready", condition_status="False", toleration_seconds=300.0
            )
        ]

    def test_unhealthy_node_repaired_after_toleration(self):
        clock, store, cloud, mgr = build_env()
        provision(mgr, store, cloud, [make_pod(f"p-{i}", cpu=0.5) for i in range(2)])
        # KwokCloudProvider doesn't expose scripted repair policies; patch
        cloud.repair_policies = lambda: [
            RepairPolicy(condition_type="Ready", condition_status="False", toleration_seconds=300.0)
        ]
        node = store.nodes()[0]
        mgr.health.observe(node.name, "Ready", "False")
        assert mgr.run_maintenance()["repaired"] == 0  # toleration not elapsed
        clock.step(301.0)
        assert mgr.run_maintenance()["repaired"] == 1

    def test_circuit_breaker(self):
        # catalog of 1/2/4-cpu shapes: each 3.5-cpu pod needs its own node
        clock, store, cloud, mgr = build_env(catalog_size=24)
        provision(
            mgr, store, cloud,
            [make_pod(f"p-{i}", cpu=3.5, memory="1Gi") for i in range(4)],
        )
        nodes = store.nodes()
        assert len(nodes) >= 2
        cloud.repair_policies = lambda: [
            RepairPolicy(condition_type="Ready", condition_status="False", toleration_seconds=1.0)
        ]
        for n in nodes:  # 100% unhealthy > 20% breaker
            mgr.health.observe(n.name, "Ready", "False")
        clock.step(10.0)
        assert mgr.run_maintenance()["repaired"] == 0
