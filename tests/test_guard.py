"""Guardrails (guard/, ISSUE 10): shadow audits over the exactness-critical
fast paths, fast-path quarantine, transactional resident state, the
dispatch watchdog, and the SESSION_LOST re-snapshot protocol.

The acceptance properties under test:

- a mid-apply exception leaves the resident session INVALIDATED, not
  poisoned — the same round re-solves full and is bit-identical to cold;
- a tripped quarantine routes the path onto its exact twin until cleared;
- a lying fast path (seeded via ``KTPU_GUARD_LIE``) is CAUGHT by the
  shadow audit: the caller gets the exact result, the path quarantines,
  the repro bundle loads and replays to a nonzero exit;
- a stalled device dispatch converts into a host-fallback solve instead
  of a hang;
- a server-side resident-session eviction surfaces as one typed
  SESSION_LOST and exactly one silent client re-snapshot.

Everything is CPU-sized for tier-1; the replay subprocess is the one
deliberately slow piece (it is the satellite's CLI contract).
"""

import json
import os
import subprocess
import sys

import pytest

from karpenter_tpu import guard
from karpenter_tpu.controllers.provisioning import TPUScheduler
from karpenter_tpu.faultinject import active_plan
from karpenter_tpu.guard import bundle as guard_bundle

from test_resident import (
    assert_identical,
    cold_solve,
    kind_pods,
    make_templates,
    session_scheduler,
)


@pytest.fixture(autouse=True)
def _clean_guard_state(monkeypatch):
    """Every test starts and ends with no quarantine, an empty audit log,
    and the guard knobs unset (rate defaults to 0 — audits off)."""
    for var in (
        "KTPU_GUARD_AUDIT_RATE",
        "KTPU_GUARD_DIR",
        "KTPU_GUARD_LIE",
        "KTPU_GUARD_TTL_S",
        "KTPU_WATCHDOG_S",
    ):
        monkeypatch.delenv(var, raising=False)
    guard.QUARANTINE.reset()
    guard.reset_log()
    yield
    guard.QUARANTINE.reset()
    guard.reset_log()


class TestTransactionalResident:
    def test_mid_apply_fault_invalidates_not_poisons(self, monkeypatch):
        """An exception between the retract and append passes (the
        worst spot: state half-mutated) must drop the resident state and
        re-solve full — bit-identical to cold — and the NEXT round is a
        healthy delta again."""
        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 12) + kind_pods("b", 8)
        session.solve(list(base))
        assert session.last_mode == "full"
        union = base + kind_pods("c", 6)
        plan = {
            "rules": [
                {
                    "point": "solver.resident.apply",
                    "error": "runtime",
                    "times": 1,
                    "match": {"stage": "mid"},
                }
            ]
        }
        with active_plan(plan):
            r = session.solve(list(union))
        assert session.last_mode == "invalidated", session.last_reason
        assert session.last_reason.startswith("apply_error:")
        assert_identical(cold_solve(union), r)
        # the session re-snapshotted during the full solve: next arrival
        # rides the delta path again, still exact
        union2 = union + kind_pods("d", 4)
        r2 = session.solve(list(union2))
        assert session.last_mode == "delta", session.last_reason
        assert_identical(cold_solve(union2), r2)

    def test_fingerprint_chains_rounds(self, monkeypatch):
        session = session_scheduler(monkeypatch)
        assert session.fingerprint == ""
        base = kind_pods("a", 10)
        session.solve(list(base))
        f1 = session.fingerprint
        assert f1
        session.solve(list(base + kind_pods("b", 5)))
        f2 = session.fingerprint
        assert f2 and f2 != f1


class TestQuarantine:
    def test_resident_quarantine_routes_to_full(self, monkeypatch):
        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 10)
        session.solve(list(base))
        guard.QUARANTINE.trip("resident", reason="test")
        union = base + kind_pods("b", 5)
        r = session.solve(list(union))
        assert session.last_mode == "full"
        assert session.last_reason == "quarantined"
        assert_identical(cold_solve(union), r)
        guard.QUARANTINE.clear("resident")
        union2 = union + kind_pods("c", 4)
        r2 = session.solve(list(union2))
        assert session.last_mode == "delta", session.last_reason
        assert_identical(cold_solve(union2), r2)

    def test_encode_cache_quarantine_bypasses_cache(self):
        from karpenter_tpu.utils.metrics import ENCODE_CACHE_HITS

        sched = TPUScheduler(make_templates(), max_claims=128)
        pods = kind_pods("a", 8) + kind_pods("b", 8)
        sched.solve(list(pods))
        before = ENCODE_CACHE_HITS.get()
        sched.solve(list(pods))
        assert ENCODE_CACHE_HITS.get() > before  # warm: rows reused
        guard.QUARANTINE.trip("encode_cache", reason="test")
        frozen = ENCODE_CACHE_HITS.get()
        r = sched.solve(list(pods))
        assert ENCODE_CACHE_HITS.get() == frozen  # bypassed outright
        assert not r.unschedulable

    def test_ttl_expiry_clears(self):
        clock = [0.0]
        q = guard.Quarantine(now=lambda: clock[0])
        q.trip("grid", reason="test", ttl_s=10.0)
        assert q.active("grid")
        clock[0] = 10.5
        assert not q.active("grid")


class TestLyingFastPaths:
    def test_lying_resident_is_caught_bundled_and_replayable(
        self, monkeypatch, tmp_path
    ):
        """The seeded lying-fast-path fixture: KTPU_GUARD_LIE=resident
        GENUINELY corrupts the delta result, so only the shadow audit
        stands between the lie and the caller. The audit must catch it,
        serve the exact twin, quarantine the path, write a bundle that
        loads, and the replay CLI must exit nonzero on it."""
        monkeypatch.setenv("KTPU_GUARD_AUDIT_RATE", "1.0")
        monkeypatch.setenv("KTPU_GUARD_LIE", "resident")
        monkeypatch.setenv("KTPU_GUARD_DIR", str(tmp_path))
        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 10) + kind_pods("b", 6)
        session.solve(list(base))  # full round: no delta, no lie yet
        union = base + kind_pods("c", 5)
        r = session.solve(list(union))
        # the caller saw the exact twin, not the lie
        assert session.last_mode == "full"
        assert session.last_reason == "guard_divergence"
        assert_identical(cold_solve(union), r)
        assert guard.divergences("resident")
        assert guard.QUARANTINE.active("resident")
        audit = session.last_timings["resident"]["audit"]
        assert audit["verdict"] == "divergence"
        bundle_path = audit["bundle"]
        assert bundle_path and os.path.exists(bundle_path)
        doc = guard_bundle.load_bundle(bundle_path)
        assert doc["path"] == "resident"
        templates, pods_by_uid, existing, rounds = guard_bundle.materialize(doc)
        assert templates and pods_by_uid and rounds
        assert all(u in pods_by_uid for rnd in rounds for u in rnd)
        # subsequent rounds stay exact while quarantined (full path)
        union2 = union + kind_pods("d", 4)
        r2 = session.solve(list(union2))
        assert session.last_mode == "full"
        assert session.last_reason == "quarantined"
        assert_identical(cold_solve(union2), r2)
        # the replay CLI reproduces the divergence (the bundle recorded
        # KTPU_GUARD_LIE, so the lying path re-arms in the child) and
        # exits nonzero
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-m", "karpenter_tpu.guard.replay", bundle_path],
            capture_output=True,
            text=True,
            timeout=420,
            env=env,
        )
        assert proc.returncode == 1, proc.stderr + proc.stdout
        summary = json.loads(proc.stdout)
        assert summary["reproduced"] is True
        assert summary["path"] == "resident"

    def test_lying_encode_cache_is_caught_and_dropped(self, monkeypatch):
        """A poisoned cache row is detected on the hit path; the caller
        gets freshly-encoded rows, the cache is dropped, the path
        quarantines — and the solve is still exact."""
        monkeypatch.setenv("KTPU_GUARD_AUDIT_RATE", "1.0")
        monkeypatch.setenv("KTPU_GUARD_LIE", "encode_cache")
        sched = TPUScheduler(make_templates(), max_claims=128)
        pods = kind_pods("a", 8) + kind_pods("b", 8)
        r1 = sched.solve(list(pods))
        r2 = sched.solve(list(pods))  # hit path -> audit fires -> lie caught
        assert guard.divergences("encode_cache")
        assert guard.QUARANTINE.active("encode_cache")
        assert r2.assignments == r1.assignments
        assert len(r2.claims) == len(r1.claims)


class TestGridAudit:
    def _zonal_pods(self):
        """Three same-request kind-scan segments: the incremental [W, T,
        GR] grid reuse fires at the segment boundaries (the fast path the
        audit shadows)."""
        from karpenter_tpu.models import labels as l
        from karpenter_tpu.models.pod import TopologySpreadConstraint
        from karpenter_tpu.models.pod import make_pod

        pods = []
        for k in range(3):
            for i in range(8):
                p = make_pod(f"z{k}-{i}", cpu=1.0, memory="1Gi")
                p.metadata.labels = {"spread": "zonal", "shard": f"s{k}"}
                p.spec.topology_spread_constraints = [
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=l.LABEL_TOPOLOGY_ZONE,
                        label_selector={"spread": "zonal"},
                    )
                ]
                pods.append(p)
        return pods

    def test_grid_audit_passes_against_full_recompute(self, monkeypatch):
        import bench

        monkeypatch.setenv("KTPU_GUARD_AUDIT_RATE", "1.0")
        pods = self._zonal_pods()
        templates = make_templates(24)
        sched = TPUScheduler(templates, max_claims=64)
        result = sched.solve(list(pods))
        host, _ = bench.host_solve(templates, pods)
        from test_solver import assert_same_packing

        assert_same_packing(host, result)
        assert any(
            rec["path"] == "grid" and rec["verdict"] == "pass"
            for rec in guard.AUDIT_LOG
        ), guard.AUDIT_LOG

    def test_lying_grid_is_caught_and_quarantined(self, monkeypatch):
        import bench

        monkeypatch.setenv("KTPU_GUARD_AUDIT_RATE", "1.0")
        monkeypatch.setenv("KTPU_GUARD_LIE", "grid")
        pods = self._zonal_pods()
        templates = make_templates(24)
        sched = TPUScheduler(templates, max_claims=64)
        result = sched.solve(list(pods))
        assert guard.divergences("grid")
        assert guard.QUARANTINE.active("grid")
        # the audit served the exact (full-recompute) twin
        host, _ = bench.host_solve(templates, pods)
        from test_solver import assert_same_packing

        assert_same_packing(host, result)
        # while quarantined the solve routes onto the full recompute and
        # stays exact
        result2 = sched.solve(list(pods))
        assert_same_packing(host, result2)


class TestSpeculativeAudit:
    def test_committed_merge_audit_passes(self, monkeypatch):
        """A rate-1.0 audit over the dp-speculative path: every committed
        merge round is re-derived via the sequential dispatch twin and
        must agree — and the solve stays bit-identical to single-device."""
        from test_shard import (
            dp_scheduler,
            make_templates as shard_templates,
            saturating_kind_pods,
        )

        monkeypatch.setenv("KTPU_GUARD_AUDIT_RATE", "1.0")
        pods = saturating_kind_pods(256)
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(pods)
        assert sched.last_timings["shard"]["groups_committed"] >= 2
        assert any(
            rec["path"] == "speculative" and rec["verdict"] == "pass"
            for rec in guard.AUDIT_LOG
        ), guard.AUDIT_LOG
        assert not guard.divergences()
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(shard_templates()).solve(pods)
        assert_identical(single, meshed)

    def test_lying_speculative_is_caught(self, monkeypatch):
        """The lying fixture corrupts the merged state the audit compares:
        the sequential twin wins, the path quarantines, and the caller
        still gets the single-device answer."""
        from test_shard import (
            dp_scheduler,
            make_templates as shard_templates,
            saturating_kind_pods,
        )

        monkeypatch.setenv("KTPU_GUARD_AUDIT_RATE", "1.0")
        monkeypatch.setenv("KTPU_GUARD_LIE", "speculative")
        pods = saturating_kind_pods(256)
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(pods)
        assert guard.divergences("speculative")
        assert guard.QUARANTINE.active("speculative")
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(shard_templates()).solve(pods)
        assert_identical(single, meshed)
        # a quarantined speculative path runs the sequential pipeline —
        # still exact
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "4")
        monkeypatch.delenv("KTPU_GUARD_LIE", raising=False)
        sched2 = dp_scheduler(monkeypatch)
        r2 = sched2.solve(pods)
        assert_identical(single, r2)
        shard = sched2.last_timings.get("shard") or {}
        assert shard.get("merge_rounds", 0) == 0, shard

    def test_lying_kscan_speculative_is_caught(self, monkeypatch):
        """Same contract for the kscan family (ISSUE 13): the sequential
        twin runs BEFORE the speculative merge, catches the corrupted
        graft, and quarantine routes subsequent kscan speculation back to
        the sequential pipeline."""
        from test_shard import (
            dp_scheduler,
            make_templates as shard_templates,
            zonal_kind_pods,
        )

        monkeypatch.setenv("KTPU_GUARD_AUDIT_RATE", "1.0")
        monkeypatch.setenv("KTPU_GUARD_LIE", "speculative")
        pods = zonal_kind_pods(192, kinds=4, prefix="gz")
        sched = dp_scheduler(monkeypatch)
        meshed = sched.solve(pods)
        assert guard.divergences("speculative")
        assert guard.QUARANTINE.active("speculative")
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "0")
        single = TPUScheduler(shard_templates()).solve(pods)
        assert_identical(single, meshed)
        monkeypatch.setenv("KTPU_PIPELINE_CHUNKS", "4")
        monkeypatch.delenv("KTPU_GUARD_LIE", raising=False)
        sched2 = dp_scheduler(monkeypatch)
        r2 = sched2.solve(pods)
        assert_identical(single, r2)
        fam = (sched2.last_timings.get("shard") or {}).get("families") or {}
        assert fam.get("kscan", {}).get("committed", 0) == 0, fam
        assert (sched2.last_timings["shard"]).get("merge_rounds", 0) == 0


class TestWatchdog:
    def test_stalled_dispatch_falls_back_to_host(self, monkeypatch):
        """A latency fault at solver.dispatch (the stand-in for a hung
        collective rendezvous) must trip the deadline thread and convert
        the solve into a host fallback — a RESULT, not a hang."""
        from karpenter_tpu.utils.metrics import SOLVER_FALLBACK, WATCHDOG_STALLS

        monkeypatch.setenv("KTPU_WATCHDOG_S", "0.3")
        sched = TPUScheduler(make_templates(), max_claims=128)
        pods = kind_pods("a", 8)
        stalls0 = WATCHDOG_STALLS.get(section="dispatch")
        fb0 = SOLVER_FALLBACK.get(reason="watchdog_dispatch")
        plan = {
            "rules": [
                {
                    "point": "solver.dispatch",
                    "mode": "latency",
                    "delay_s": 2.0,
                    "times": 1,
                }
            ]
        }
        with active_plan(plan):
            r = sched.solve(list(pods))
        assert WATCHDOG_STALLS.get(section="dispatch") == stalls0 + 1
        assert SOLVER_FALLBACK.get(reason="watchdog_dispatch") == fb0 + 1
        assert not r.unschedulable
        assert set(r.assignments) == {p.uid for p in pods}

    def test_disabled_watchdog_is_a_direct_call(self):
        from karpenter_tpu.guard.watchdog import run_guarded

        # deadline <= 0: no worker thread, the callable runs inline
        assert run_guarded(lambda: 41 + 1, section="test") == 42


class TestSessionLost:
    def test_forced_eviction_is_one_silent_resnapshot(self):
        """An injected rpc.session.evict (server restart / registry LRU
        stand-in) makes the NEXT Solve observe a typed SESSION_LOST; the
        client recovers with exactly ONE silent snapshot re-solve counted
        under ktpu_resident_rounds_total{mode="invalidated"}."""
        from karpenter_tpu.rpc import RemoteScheduler, serve
        from karpenter_tpu.utils.metrics import RESIDENT_ROUNDS

        # the same config the resident differential suite uses: the adopt
        # gate accepts it, so the server goes resident and fingerprints
        templates = make_templates()
        server, addr = serve("127.0.0.1:0")
        try:
            remote = RemoteScheduler(addr, templates, max_claims=128)
            base = kind_pods("a", 10)
            remote.solve(list(base))
            # the server echoed its fingerprint in trailing metadata
            assert remote._session_fpr
            union = base + kind_pods("b", 5)
            remote.solve(list(union))
            fpr_before = remote._session_fpr
            assert fpr_before
            inv0 = RESIDENT_ROUNDS.get(mode="invalidated")
            union2 = union + kind_pods("c", 4)
            plan = {
                "rules": [
                    {"point": "rpc.session.evict", "error": "runtime", "times": 1}
                ]
            }
            with active_plan(plan):
                # the in-process server shares the global injector: its
                # registry lookup fires the rule and force-evicts
                r = remote.solve(list(union2))
            assert RESIDENT_ROUNDS.get(mode="invalidated") == inv0 + 1
            local = TPUScheduler(templates, max_claims=128).solve(list(union2))
            assert r.assignments == local.assignments
            assert len(r.claims) == len(local.claims)
            # the retry re-snapshotted: a fresh fingerprint came back
            assert remote._session_fpr
            assert remote._session_fpr != fpr_before
        finally:
            server.stop(0)


class TestAuditPlumbing:
    def test_should_audit_rate_gate(self, monkeypatch):
        monkeypatch.setenv("KTPU_GUARD_AUDIT_RATE", "0")
        assert not guard.should_audit("resident")
        monkeypatch.setenv("KTPU_GUARD_AUDIT_RATE", "1.0")
        assert guard.should_audit("resident")
        guard.QUARANTINE.trip("resident", reason="test")
        # a quarantined path runs its exact twin ANYWAY: auditing it
        # would re-derive the same computation twice for nothing
        assert not guard.should_audit("resident")

    def test_passing_audit_counts_and_keeps_delta(self, monkeypatch, tmp_path):
        monkeypatch.setenv("KTPU_GUARD_AUDIT_RATE", "1.0")
        monkeypatch.setenv("KTPU_GUARD_DIR", str(tmp_path))
        session = session_scheduler(monkeypatch)
        base = kind_pods("a", 10)
        session.solve(list(base))
        union = base + kind_pods("b", 5)
        r = session.solve(list(union))
        assert session.last_mode == "delta", session.last_reason
        audit = session.last_timings["resident"]["audit"]
        assert audit["verdict"] == "pass"
        assert audit["twin_s"] >= 0
        assert not guard.divergences()
        assert not os.listdir(tmp_path)  # no bundle on a passing audit
        assert_identical(cold_solve(union), r)


def test_pod_roundtrip_through_bundle():
    """bundle.make_bundle/materialize preserves the solve inputs."""
    sched = TPUScheduler(make_templates(), max_claims=128)
    pods = kind_pods("a", 4)
    doc = guard_bundle.make_bundle(
        "resident",
        "unit-test",
        sched,
        {p.uid: p for p in pods},
        [[p.uid for p in pods]],
        [],
        detail={"k": 1},
    )
    templates, pods_by_uid, existing, rounds = guard_bundle.materialize(doc)
    assert sorted(pods_by_uid) == sorted(p.uid for p in pods)
    assert rounds == [[p.uid for p in pods]]
    assert existing == []
    assert len(templates) == len(sched.templates)
    r_orig = TPUScheduler(make_templates(), max_claims=128).solve(list(pods))
    r_rt = TPUScheduler(templates, max_claims=128).solve(
        [pods_by_uid[u] for u in rounds[0]]
    )
    assert r_rt.assignments == r_orig.assignments


def test_bundle_env_pins_shard_family_knobs(monkeypatch):
    """ISSUE 20: the bundle env snapshots the shard family opt-out knobs
    even when UNSET — "unset" (dp-eligible) is itself a routing input, and
    a replay host where one happens to be exported would route the family
    differently and never reach the diverging merge. An empty string
    restores the default: every knob reads `get(k, "1") not in ("0", ...)`,
    so "" and unset route identically."""
    for knob in ("KTPU_SHARD_EXISTING", "KTPU_SHARD_PERPOD", "KTPU_SHARD_KSCAN"):
        monkeypatch.delenv(knob, raising=False)
    monkeypatch.setenv("KTPU_SHARD_PERPOD", "0")
    sched = TPUScheduler(make_templates(), max_claims=128)
    pods = kind_pods("e", 2)
    doc = guard_bundle.make_bundle(
        "speculative", "unit-test", sched, {p.uid: p for p in pods},
        [[p.uid for p in pods]], [],
    )
    env = doc["env"]
    assert env["KTPU_SHARD_PERPOD"] == "0"  # the set value survives
    assert env["KTPU_SHARD_EXISTING"] == ""  # unset is pinned, not dropped
    assert env["KTPU_SHARD_KSCAN"] == ""
