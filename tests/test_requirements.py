"""Requirement/Requirements set-algebra semantics.

These mirror the behavioral contract of reference
pkg/scheduling/requirement_test.go / requirements_test.go (cases re-derived
from the documented semantics, not copied).
"""

import pytest

from karpenter_tpu.models import labels as l
from karpenter_tpu.scheduling import Operator, Requirement, Requirements


def req(key, op, *values, min_values=None):
    return Requirement.new(key, op, *values, min_values=min_values)


class TestConstruction:
    def test_in(self):
        r = req("key", Operator.IN, "a", "b")
        assert not r.complement
        assert r.values == {"a", "b"}
        assert r.operator() is Operator.IN

    def test_not_in(self):
        r = req("key", Operator.NOT_IN, "a")
        assert r.complement
        assert r.operator() is Operator.NOT_IN

    def test_exists(self):
        r = req("key", Operator.EXISTS)
        assert r.complement and not r.values
        assert r.operator() is Operator.EXISTS

    def test_does_not_exist(self):
        r = req("key", Operator.DOES_NOT_EXIST)
        assert not r.complement and not r.values
        assert r.operator() is Operator.DOES_NOT_EXIST

    def test_gt_canonicalized_to_gte(self):
        r = req("key", Operator.GT, "5")
        assert r.gte == 6 and r.lte is None and r.complement

    def test_lt_canonicalized_to_lte(self):
        r = req("key", Operator.LT, "5")
        assert r.lte == 4 and r.gte is None and r.complement

    def test_gte_lte(self):
        assert req("key", Operator.GTE, "5").gte == 5
        assert req("key", Operator.LTE, "5").lte == 5

    def test_label_normalization(self):
        r = req(l.LABEL_ZONE_BETA, Operator.IN, "us-west-2a")
        assert r.key == l.LABEL_TOPOLOGY_ZONE


class TestHas:
    def test_in(self):
        r = req("key", Operator.IN, "a")
        assert r.has("a") and not r.has("b")

    def test_not_in(self):
        r = req("key", Operator.NOT_IN, "a")
        assert not r.has("a") and r.has("b")

    def test_exists(self):
        assert req("key", Operator.EXISTS).has("anything")

    def test_does_not_exist(self):
        assert not req("key", Operator.DOES_NOT_EXIST).has("anything")

    def test_bounds_admit_only_integers(self):
        r = req("key", Operator.GT, "3")
        assert r.has("4") and not r.has("3") and not r.has("abc")

    def test_lt(self):
        r = req("key", Operator.LT, "3")
        assert r.has("2") and not r.has("3")


class TestIntersection:
    def test_in_in(self):
        r = req("key", Operator.IN, "a", "b").intersection(req("key", Operator.IN, "b", "c"))
        assert r.values == {"b"} and not r.complement

    def test_in_in_disjoint_is_does_not_exist(self):
        r = req("key", Operator.IN, "a").intersection(req("key", Operator.IN, "b"))
        assert r.operator() is Operator.DOES_NOT_EXIST

    def test_in_not_in(self):
        r = req("key", Operator.IN, "a", "b").intersection(req("key", Operator.NOT_IN, "a"))
        assert r.values == {"b"} and not r.complement

    def test_not_in_not_in_unions_exclusions(self):
        r = req("key", Operator.NOT_IN, "a").intersection(req("key", Operator.NOT_IN, "b"))
        assert r.complement and r.values == {"a", "b"}

    def test_exists_in(self):
        r = req("key", Operator.EXISTS).intersection(req("key", Operator.IN, "a"))
        assert not r.complement and r.values == {"a"}

    def test_empty_bounds_is_does_not_exist(self):
        r = req("key", Operator.GTE, "5").intersection(req("key", Operator.LTE, "3"))
        assert r.operator() is Operator.DOES_NOT_EXIST

    def test_bounds_filter_values(self):
        r = req("key", Operator.IN, "1", "5", "9").intersection(req("key", Operator.LT, "6"))
        assert r.values == {"1", "5"}
        # concrete sets drop bounds
        assert r.gte is None and r.lte is None

    def test_bounds_merge_on_complements(self):
        r = req("key", Operator.GT, "1").intersection(req("key", Operator.LT, "9"))
        assert r.complement and r.gte == 2 and r.lte == 8

    def test_min_values_max_wins(self):
        a = req("key", Operator.IN, "a", "b", min_values=2)
        b = req("key", Operator.IN, "a", "b", "c", min_values=3)
        assert a.intersection(b).min_values == 3

    def test_commutative_nonempty(self):
        cases = [
            req("k", Operator.IN, "a", "b"),
            req("k", Operator.NOT_IN, "b", "c"),
            req("k", Operator.EXISTS),
            req("k", Operator.DOES_NOT_EXIST),
            req("k", Operator.GT, "2"),
            req("k", Operator.LT, "7"),
            req("k", Operator.IN, "3", "5"),
        ]
        for a in cases:
            for b in cases:
                ab, ba = a.intersection(b), b.intersection(a)
                assert ab.values == ba.values
                assert ab.complement == ba.complement
                assert ab.gte == ba.gte and ab.lte == ba.lte


class TestHasIntersection:
    CASES = [
        req("k", Operator.IN, "a", "b"),
        req("k", Operator.IN, "b"),
        req("k", Operator.IN, "5"),
        req("k", Operator.NOT_IN, "a"),
        req("k", Operator.NOT_IN, "5"),
        req("k", Operator.EXISTS),
        req("k", Operator.DOES_NOT_EXIST),
        req("k", Operator.GT, "3"),
        req("k", Operator.LT, "3"),
        req("k", Operator.GTE, "5"),
        req("k", Operator.LTE, "5"),
    ]

    def test_matches_full_intersection_nonemptiness(self):
        # has_intersection must agree with "intersection() is non-empty"
        for a in self.CASES:
            for b in self.CASES:
                full = a.intersection(b)
                # non-empty: any finite values, or complement (infinite set)
                nonempty = bool(full.values) or full.complement
                assert a.has_intersection(b) == nonempty, f"{a} vs {b}"

    def test_symmetric(self):
        for a in self.CASES:
            for b in self.CASES:
                assert a.has_intersection(b) == b.has_intersection(a)


class TestRequirements:
    def test_add_intersects_per_key(self):
        rs = Requirements(req("k", Operator.IN, "a", "b"))
        rs.add(req("k", Operator.IN, "b", "c"))
        assert rs.get("k").values == {"b"}

    def test_get_missing_is_exists(self):
        rs = Requirements()
        assert rs.get("missing").operator() is Operator.EXISTS

    def test_compatible_well_known_undefined_allowed(self):
        node = Requirements()  # defines nothing
        pod = Requirements(req(l.LABEL_TOPOLOGY_ZONE, Operator.IN, "zone-1"))
        assert node.compatible(pod, allow_undefined=l.WELL_KNOWN_LABELS) is None

    def test_compatible_custom_undefined_denied(self):
        node = Requirements()
        pod = Requirements(req("custom", Operator.IN, "x"))
        assert node.compatible(pod, allow_undefined=l.WELL_KNOWN_LABELS) is not None

    def test_compatible_custom_undefined_lenient_ops_allowed(self):
        node = Requirements()
        for op in (Operator.NOT_IN, Operator.DOES_NOT_EXIST):
            pod = Requirements(req("custom", op, "x") if op is Operator.NOT_IN else req("custom", op))
            assert node.compatible(pod, allow_undefined=l.WELL_KNOWN_LABELS) is None

    def test_intersects_shared_keys_only(self):
        a = Requirements(req("a", Operator.IN, "1"), req("shared", Operator.IN, "x"))
        b = Requirements(req("b", Operator.IN, "2"), req("shared", Operator.IN, "x", "y"))
        assert a.intersects(b) is None

    def test_intersects_conflict(self):
        a = Requirements(req("shared", Operator.IN, "x"))
        b = Requirements(req("shared", Operator.IN, "y"))
        assert a.intersects(b) is not None

    def test_intersects_double_lenient_forgiven(self):
        # DoesNotExist vs NotIn: no value intersection but both lenient
        a = Requirements(req("k", Operator.DOES_NOT_EXIST))
        b = Requirements(req("k", Operator.NOT_IN, "x"))
        assert a.intersects(b) is None

    def test_intersects_does_not_exist_vs_in_fails(self):
        a = Requirements(req("k", Operator.DOES_NOT_EXIST))
        b = Requirements(req("k", Operator.IN, "x"))
        assert a.intersects(b) is not None

    def test_labels_roundtrip(self):
        rs = Requirements.from_labels({"a": "1", "b": "2"})
        assert rs.labels() == {"a": "1", "b": "2"}

    def test_has_min_values(self):
        assert not Requirements(req("k", Operator.IN, "a")).has_min_values()
        assert Requirements(req("k", Operator.IN, "a", min_values=1)).has_min_values()


class TestPodRequirements:
    def test_node_selector_and_required_affinity(self):
        from karpenter_tpu.models.pod import NodeAffinity, NodeSelectorTerm, make_pod

        pod = make_pod("p", node_selector={"disk": "ssd"})
        pod.spec.node_affinity = NodeAffinity(
            required=[
                NodeSelectorTerm([{"key": "zone", "operator": "In", "values": ["a", "b"]}]),
                NodeSelectorTerm([{"key": "zone", "operator": "In", "values": ["c"]}]),  # OR'd; only first used
            ]
        )
        rs = Requirements.from_pod(pod)
        assert rs.get("disk").values == {"ssd"}
        assert rs.get("zone").values == {"a", "b"}

    def test_heaviest_preference_treated_as_required(self):
        from karpenter_tpu.models.pod import NodeAffinity, PreferredSchedulingTerm, make_pod

        pod = make_pod("p")
        pod.spec.node_affinity = NodeAffinity(
            preferred=[
                PreferredSchedulingTerm(1, [{"key": "zone", "operator": "In", "values": ["a"]}]),
                PreferredSchedulingTerm(10, [{"key": "zone", "operator": "In", "values": ["b"]}]),
            ]
        )
        rs = Requirements.from_pod(pod)
        assert rs.get("zone").values == {"b"}
        strict = Requirements.from_pod(pod, include_preferred=False)
        assert not strict.has("zone")


class TestBudgetReasons:
    def test_reason_scoped_budget(self):
        from karpenter_tpu.models.nodepool import Budget, NodePool

        pool = NodePool()
        pool.spec.disruption.budgets = [
            Budget(nodes="0", reasons=["Drifted"]),  # freeze drift disruptions
            Budget(nodes="50%"),  # everything else at 50%
        ]
        now = 1_700_000_000.0
        assert pool.allowed_disruptions("Drifted", total_nodes=10, now=now) == 0
        assert pool.allowed_disruptions("Underutilized", total_nodes=10, now=now) == 5
        assert pool.allowed_disruptions("Empty", total_nodes=10, now=now) == 5

    def test_all_reason_budget(self):
        from karpenter_tpu.models.nodepool import Budget, NodePool

        pool = NodePool()
        pool.spec.disruption.budgets = [Budget(nodes="2", reasons=["All"])]
        now = 1_700_000_000.0
        for reason in ("Drifted", "Underutilized", "Empty"):
            assert pool.allowed_disruptions(reason, total_nodes=10, now=now) == 2

    def test_min_across_active_budgets(self):
        from karpenter_tpu.models.nodepool import Budget, NodePool

        pool = NodePool()
        pool.spec.disruption.budgets = [Budget(nodes="4"), Budget(nodes="30%")]
        now = 1_700_000_000.0
        # min(4, floor(10 * 0.3)) = 3
        assert pool.allowed_disruptions("Empty", total_nodes=10, now=now) == 3

    def test_inactive_window_ignored(self):
        import calendar

        from karpenter_tpu.models.nodepool import Budget, NodePool

        pool = NodePool()
        pool.spec.disruption.budgets = [
            Budget(nodes="0", schedule="0 9 * * 1-5", duration_seconds=3600.0)
        ]
        # Wed 2026-07-29 09:30 UTC — inside the freeze window
        inside = calendar.timegm((2026, 7, 29, 9, 30, 0, 0, 0, 0))
        # Wed 2026-07-29 12:00 UTC — outside
        outside = calendar.timegm((2026, 7, 29, 12, 0, 0, 0, 0, 0))
        assert pool.allowed_disruptions("Empty", total_nodes=10, now=inside) == 0
        assert pool.allowed_disruptions("Empty", total_nodes=10, now=outside) == 10


class TestTaints:
    def test_tolerates(self):
        from karpenter_tpu.models.taints import NO_SCHEDULE, Taint, Toleration
        from karpenter_tpu.scheduling import tolerates_all

        taints = [Taint(key="team", value="a", effect=NO_SCHEDULE)]
        assert tolerates_all(taints, []) is not None
        assert tolerates_all(taints, [Toleration(key="team", operator="Equal", value="a")]) is None
        assert tolerates_all(taints, [Toleration(key="team", operator="Exists")]) is None
        assert tolerates_all(taints, [Toleration(operator="Exists")]) is None
        assert tolerates_all(taints, [Toleration(key="team", operator="Equal", value="b")]) is not None
        # effect-scoped toleration
        assert tolerates_all(taints, [Toleration(key="team", operator="Exists", effect="NoExecute")]) is not None
