"""Ops-parity subsystems: metrics, events, options, static pools, buffers."""

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.capacity_buffer import CapacityBuffer, is_buffer_pod
from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import PodSpec, make_pod
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.events import Event, Recorder, failed_scheduling
from karpenter_tpu.utils.metrics import Registry
from karpenter_tpu.utils.options import FeatureGates, Options


def build_env(catalog_size=50):
    clock = FakeClock()
    store = ObjectStore(clock)
    cloud = KwokCloudProvider(store, catalog=instance_types(catalog_size))
    mgr = Manager(store, cloud, clock)
    return clock, store, cloud, mgr


class TestMetrics:
    def test_counter_gauge_histogram(self):
        reg = Registry()
        c = reg.counter("test_total", "a counter", ("pool",))
        c.inc(pool="a")
        c.inc(2.0, pool="a")
        assert c.get(pool="a") == 3.0
        g = reg.gauge("test_gauge", "a gauge")
        g.set(5.0)
        assert g.get() == 5.0
        h = reg.histogram("test_seconds", "a histogram")
        h.observe(0.05)
        h.observe(0.2)
        assert h.totals[()] == 2
        text = reg.expose()
        assert 'test_total{pool="a"} 3.0' in text
        assert "# TYPE test_seconds histogram" in text

    def test_histogram_timer(self):
        reg = Registry()
        h = reg.histogram("t_seconds", "")
        with h.time():
            pass
        assert h.totals[()] == 1


class TestEvents:
    def test_dedupe_within_ttl(self):
        clock = FakeClock()
        rec = Recorder(clock)
        assert rec.publish(failed_scheduling("p1", "no capacity"))
        assert not rec.publish(failed_scheduling("p1", "no capacity"))  # deduped
        assert len(rec.events) == 1
        assert rec.events[0].count == 2
        clock.step(121.0)
        assert rec.publish(failed_scheduling("p1", "no capacity"))  # TTL expired

    def test_distinct_not_deduped(self):
        rec = Recorder(FakeClock())
        assert rec.publish(failed_scheduling("p1", "a"))
        assert rec.publish(failed_scheduling("p2", "a"))
        assert len(rec.for_object("Pod", "p1")) == 1


class TestOptions:
    def test_feature_gate_parsing(self):
        gates = FeatureGates.parse("SpotToSpotConsolidation=true,NodeRepair=true")
        assert gates.spot_to_spot_consolidation and gates.node_repair
        assert gates.reserved_capacity  # default preserved

    def test_defaults_match_reference(self):
        opts = Options()
        assert opts.batch_idle_seconds == 1.0
        assert opts.batch_max_seconds == 10.0
        assert not opts.feature_gates.spot_to_spot_consolidation


class TestOperator:
    def test_operator_wiring_and_tick(self):
        from karpenter_tpu.operator import Operator
        from karpenter_tpu.utils.options import FeatureGates, Options

        clock = FakeClock()
        opts = Options(feature_gates=FeatureGates.parse("SpotToSpotConsolidation=true"))
        op = Operator.new(clock=clock, options=opts)
        # feature gate propagated into the consolidation methods
        from karpenter_tpu.controllers.disruption.methods import (
            MultiNodeConsolidation,
            SingleNodeConsolidation,
        )

        consolidators = [
            m
            for m in op.manager.disruption.methods
            if isinstance(m, (MultiNodeConsolidation, SingleNodeConsolidation))
        ]
        assert len(consolidators) == 2
        assert all(m.spot_to_spot_enabled for m in consolidators)
        pool = NodePool()
        pool.metadata.name = "default"
        op.store.create(ObjectStore.NODEPOOLS, pool)
        op.store.create(ObjectStore.PODS, make_pod("p", cpu=0.5))
        op.tick()
        op.cloud.unwrapped.simulate_kubelet_ready()
        op.tick()
        assert len(op.store.nodes()) == 1
        assert all(p.spec.node_name for p in op.store.pods())


class TestMetricsWiring:
    def test_provisioning_and_disruption_emit_metrics(self):
        from karpenter_tpu.utils import metrics

        clock, store, cloud, mgr = build_env()
        pool = NodePool()
        pool.metadata.name = "default"
        from karpenter_tpu.models.nodepool import Budget

        pool.spec.disruption.budgets = [Budget(nodes="100%")]
        store.create(ObjectStore.NODEPOOLS, pool)
        before = metrics.NODECLAIMS_CREATED.get(
            reason="provisioning", nodepool="default", min_values_relaxed="false"
        )
        store.create(ObjectStore.PODS, make_pod("p", cpu=1.0))
        mgr.run_until_idle()
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        KubeSchedulerSim(store, mgr.cluster).bind_pending()
        assert (
            metrics.NODECLAIMS_CREATED.get(
                reason="provisioning", nodepool="default", min_values_relaxed="false"
            )
            > before
        )
        assert metrics.SCHEDULING_DURATION.totals[()] > 0
        mgr.run_maintenance()
        assert metrics.NODEPOOL_USAGE.get(nodepool="default", resource_type="nodes") >= 1.0
        exposition = metrics.REGISTRY.expose()
        assert "karpenter_nodeclaims_created_total" in exposition


class TestStaticCapacity:
    def test_scale_up_to_replicas(self):
        clock, store, cloud, mgr = build_env()
        pool = NodePool()
        pool.metadata.name = "static"
        pool.spec.replicas = 3
        store.create(ObjectStore.NODEPOOLS, pool)
        out = mgr.run_maintenance()
        assert out["static_delta"] == 3
        assert len(store.nodeclaims()) == 3
        cloud.simulate_kubelet_ready()
        mgr.run_until_idle()
        assert len(store.nodes()) == 3
        # steady state: no churn
        assert mgr.run_maintenance()["static_delta"] == 0

    def test_scale_down(self):
        clock, store, cloud, mgr = build_env()
        pool = NodePool()
        pool.metadata.name = "static"
        pool.spec.replicas = 3
        store.create(ObjectStore.NODEPOOLS, pool)
        mgr.run_maintenance()
        pool.spec.replicas = 1
        store.update(ObjectStore.NODEPOOLS, pool)
        out = mgr.run_maintenance()
        assert out["static_delta"] == -2
        assert len([c for c in store.nodeclaims() if not c.metadata.deleting]) == 1

    def test_static_pools_not_used_for_dynamic_provisioning(self):
        clock, store, cloud, mgr = build_env()
        pool = NodePool()
        pool.metadata.name = "static"
        pool.spec.replicas = 1
        store.create(ObjectStore.NODEPOOLS, pool)
        store.create(ObjectStore.PODS, make_pod("p", cpu=0.5))
        mgr.run_until_idle()
        # no dynamic pool exists -> pod cannot be provisioned
        assert all(
            c.nodepool_name == "static" for c in store.nodeclaims()
        )


class TestCapacityBuffers:
    def test_buffer_provisions_headroom(self):
        clock, store, cloud, mgr = build_env()
        pool = NodePool()
        pool.metadata.name = "default"
        store.create(ObjectStore.NODEPOOLS, pool)
        buffer = CapacityBuffer(replicas=3)
        buffer.metadata.name = "warm"
        buffer.pod_template = PodSpec(
            requests={res.CPU: 1.0, res.MEMORY: float(2**30)}
        )
        store.create(ObjectStore.CAPACITY_BUFFERS, buffer)
        mgr.batcher.trigger()
        clock.step(2.0)
        mgr.run_until_idle()
        claims = store.nodeclaims()
        assert claims, "buffer produced no headroom claims"
        total_cpu = sum(c.spec.requests.get("cpu", 0) for c in claims)
        assert total_cpu >= 3.0
        # virtual pods never appear in the store
        assert all(not is_buffer_pod(p) for p in store.pods())

    def test_buffer_headroom_not_double_provisioned(self):
        clock, store, cloud, mgr = build_env()
        pool = NodePool()
        pool.metadata.name = "default"
        store.create(ObjectStore.NODEPOOLS, pool)
        buffer = CapacityBuffer(replicas=2)
        buffer.metadata.name = "warm"
        buffer.pod_template = PodSpec(requests={res.CPU: 1.0})
        store.create(ObjectStore.CAPACITY_BUFFERS, buffer)
        mgr.batcher.trigger()
        clock.step(2.0)
        mgr.run_until_idle()
        n_claims = len(store.nodeclaims())
        # another pass must not re-provision the same headroom
        mgr.batcher.trigger()
        clock.step(2.0)
        mgr.run_until_idle()
        assert len(store.nodeclaims()) == n_claims
