"""Resource-envelope e2e suite + sampler unit tests.

The in-process counterpart of the reference e2e performance suite
(test/suites/performance/basic_test.go:50-81, thresholds.go:28-43):
scale-out, consolidation, drift and hostname-spread run end-to-end on the
kwok provider + fake clock while the envelope sampler watches host RSS and
CPU, and each scenario must land inside its Envelope (wall, P95 RSS
growth, average cores). Throughput has its gates in test_perf_gate.py;
this file pins the footprint.
"""

from __future__ import annotations

import time

import pytest

from karpenter_tpu.envelope import (
    SCENARIOS,
    Envelope,
    EnvelopeExceeded,
    ResourceSampler,
    measured,
    percentile,
    read_cpu_seconds,
    read_rss_bytes,
    run_scenario,
)


def _busy(seconds: float) -> float:
    """Burn CPU for ~seconds; returns a value so the loop can't be elided."""
    deadline = time.perf_counter() + seconds
    acc = 0.0
    while time.perf_counter() < deadline:
        acc += sum(i * i for i in range(512))
    return acc


class TestSampler:
    def test_cpu_series_monotone(self):
        """getrusage CPU is cumulative: successive readings around real
        work must be non-decreasing, and busy work must advance them."""
        readings = [read_cpu_seconds()]
        for _ in range(3):
            _busy(0.05)
            readings.append(read_cpu_seconds())
        assert readings == sorted(readings)
        assert readings[-1] > readings[0]

    def test_rss_read_positive(self):
        assert read_rss_bytes() > 10 * 2**20  # a Python+JAX process

    def test_percentile_math_on_synthetic_series(self):
        """Nearest-rank percentiles, the exact form the envelopes assert."""
        series = list(range(1, 101))  # 1..100
        assert percentile(series, 0.50) == 50
        assert percentile(series, 0.95) == 95
        assert percentile(series, 1.00) == 100
        assert percentile([7.0], 0.95) == 7.0
        assert percentile([3.0, 1.0, 2.0], 0.5) == 2.0  # unsorted input
        import math

        assert math.isnan(percentile([], 0.95))

    def test_stage_nesting(self):
        sampler = ResourceSampler(interval_s=0.02)
        with sampler:
            with sampler.stage("outer"):
                _busy(0.05)
                with sampler.stage("inner"):
                    _busy(0.05)
                _busy(0.05)
        outer, inner = sampler.stats["outer"], sampler.stats["inner"]
        assert inner.wall_s < outer.wall_s
        assert inner.cpu_s <= outer.cpu_s + 1e-6
        # both stages got their own RSS series (endpoints + thread ticks)
        assert inner.samples >= 2 and outer.samples > inner.samples
        assert outer.avg_cores > 0.3  # the block was pure compute

    def test_stats_survive_exceptions(self):
        sampler = ResourceSampler(interval_s=0.02)
        with sampler:
            with pytest.raises(RuntimeError):
                with sampler.stage("doomed"):
                    raise RuntimeError("scenario blew up")
        assert "doomed" in sampler.stats  # the envelope still closed

    def test_sampler_overhead_under_one_percent(self):
        """The sampler self-times its ticks (thread CPU seconds — a tick
        parked on the GIL behind the busy loop is time the workload RAN,
        not sampling cost): over a busy-loop stage the cumulative tick
        cost must stay under 1% of the stage wall — the guard that keeps
        envelope measurement from perturbing what it measures (the
        reference scrapes out-of-process for the same reason)."""
        sampler = ResourceSampler(interval_s=0.05)
        with sampler:
            with sampler.stage("busy"):
                _busy(0.5)
        stats = sampler.stats["busy"]
        assert stats.samples >= 3  # the thread actually ticked
        assert sampler.overhead_s < 0.01 * stats.wall_s, (
            f"sampler spent {sampler.overhead_s * 1000:.2f}ms sampling a "
            f"{stats.wall_s:.2f}s stage"
        )

    def test_metrics_gauges_published(self):
        from karpenter_tpu.utils import metrics

        sampler = ResourceSampler(interval_s=0.01)
        with sampler:
            time.sleep(0.1)
        assert metrics.HOST_RSS_BYTES.get() > 0
        assert metrics.HOST_CPU_SECONDS.get() > 0

    def test_tracemalloc_peak_behind_flag(self):
        sampler = ResourceSampler(interval_s=0.05, trace_python_alloc=True)
        with sampler:
            with sampler.stage("alloc"):
                blob = [bytes(1024) for _ in range(4096)]  # ~4MB of objects
        del blob
        peak = sampler.stats["alloc"].tracemalloc_peak_mb
        assert peak is not None and peak > 3.0
        # default-off: no tracemalloc cost on the normal path
        plain = ResourceSampler(interval_s=0.05)
        with plain:
            with plain.stage("alloc"):
                pass
        assert plain.stats["alloc"].tracemalloc_peak_mb is None

    def test_measured_fills_bench_keys(self):
        """The contract every bench.py stage dict rides on."""
        out = {}
        with measured(out, stage="unit"):
            _busy(0.05)
        assert set(out) >= {"host_rss_mb", "cpu_s", "avg_cores"}
        assert out["host_rss_mb"] > 0 and out["cpu_s"] > 0


class TestEnvelopeSpec:
    def test_violations_enumerated(self):
        from karpenter_tpu.envelope.sampler import StageStats

        stats = StageStats(
            name="x", wall_s=10.0, cpu_s=40.0, avg_cores=4.0,
            rss_mb_p50=900.0, rss_mb_p95=1000.0, rss_mb_max=1100.0, samples=10,
        )
        env = Envelope(max_wall_s=5.0, max_rss_mb_p95=200.0, max_cpu_cores=2.0)
        breaches = env.violations(stats, baseline_rss_mb=500.0)
        assert len(breaches) == 3  # wall, rss growth (500 > 200), cores
        with pytest.raises(EnvelopeExceeded):
            env.check(stats, baseline_rss_mb=500.0)
        # inside the envelope: growth 1000-900=100 < 200 etc.
        ok = Envelope(max_wall_s=20.0, max_rss_mb_p95=200.0, max_cpu_cores=8.0)
        assert ok.violations(stats, baseline_rss_mb=900.0) == []

    def test_cpu_seconds_ceiling_optional(self):
        from karpenter_tpu.envelope.sampler import StageStats

        stats = StageStats(
            name="x", wall_s=1.0, cpu_s=9.0, avg_cores=1.0,
            rss_mb_p50=0.0, rss_mb_p95=0.0, rss_mb_max=0.0, samples=2,
        )
        assert Envelope(10.0, 100.0, 2.0).violations(stats) == []
        assert Envelope(10.0, 100.0, 2.0, max_cpu_s=5.0).violations(stats)


class TestScenarioEnvelopes:
    """The e2e rows (basic_test.go:50-81): each scenario drives the full
    kwok + fake-clock lifecycle and must stay inside its envelope."""

    def test_scale_out_envelope(self):
        result = run_scenario("scale_out")  # asserts the Envelope
        assert result.detail["pods"] == 500
        assert result.detail["nodes"] >= 1
        assert result.stats.samples >= 2

    def test_consolidation_envelope(self):
        result = run_scenario("consolidation")
        assert result.detail["cpu_after"] < result.detail["cpu_before"]

    def test_drift_envelope(self):
        result = run_scenario("drift")
        assert result.detail["claims_replaced"] >= 1

    def test_hostname_spread_envelope(self):
        result = run_scenario("hostname_spread")
        assert result.detail["skew"] <= 1

    def test_registry_covers_reference_rows(self):
        assert {"scale_out", "consolidation", "drift", "hostname_spread"} <= set(
            SCENARIOS
        )
        for _fn, env in SCENARIOS.values():
            assert env.max_wall_s <= 120.0  # the reference scale-out bound

    def test_breach_detected(self):
        """An impossible envelope must fail loudly — proves the assertion
        path is live, not vacuous."""
        with pytest.raises(EnvelopeExceeded):
            run_scenario(
                "hostname_spread",
                envelope=Envelope(max_wall_s=1e-9, max_rss_mb_p95=1e9, max_cpu_cores=1e9),
            )
