"""Backoff math + circuit-breaker transitions (rpc/retry.py) — pure
unit, no sockets: deterministic jitter under a seeded RNG, cap/ceiling
behavior, and the closed -> open -> half-open -> closed lattice on an
injected clock."""

import random

import pytest

from karpenter_tpu.rpc.retry import (
    Backoff,
    CircuitBreaker,
    InjectedRpcError,
    injected_rpc_error,
    is_transient_code,
)


class TestBackoff:
    def test_seeded_jitter_is_deterministic(self):
        a = Backoff(base_s=0.1, cap_s=5.0, rng=random.Random(42))
        b = Backoff(base_s=0.1, cap_s=5.0, rng=random.Random(42))
        assert [a.delay(i) for i in range(10)] == [b.delay(i) for i in range(10)]

    def test_different_seeds_differ(self):
        a = Backoff(base_s=0.1, cap_s=5.0, rng=random.Random(1))
        b = Backoff(base_s=0.1, cap_s=5.0, rng=random.Random(2))
        assert [a.delay(i) for i in range(10)] != [b.delay(i) for i in range(10)]

    def test_ceiling_is_exponential_then_capped(self):
        b = Backoff(base_s=0.25, cap_s=2.0, multiplier=2.0)
        assert b.ceiling(0) == 0.25
        assert b.ceiling(1) == 0.5
        assert b.ceiling(2) == 1.0
        assert b.ceiling(3) == 2.0
        assert b.ceiling(4) == 2.0  # capped
        assert b.ceiling(50) == 2.0  # no overflow past the cap

    def test_jitter_stays_inside_the_band(self):
        b = Backoff(base_s=0.1, cap_s=30.0, jitter_frac=0.5, rng=random.Random(7))
        for attempt in range(12):
            raw = b.ceiling(attempt)
            for _ in range(50):
                d = b.delay(attempt)
                assert raw * 0.5 <= d <= raw, (attempt, d, raw)

    def test_zero_jitter_is_exact(self):
        b = Backoff(base_s=0.1, cap_s=1.0, jitter_frac=0.0)
        assert [b.delay(i) for i in range(5)] == [b.ceiling(i) for i in range(5)]

    def test_bad_jitter_frac_rejected(self):
        with pytest.raises(ValueError):
            Backoff(jitter_frac=1.5)


class TestCircuitBreaker:
    def _breaker(self, threshold=3, cooldown=10.0):
        t = [0.0]
        seen = []
        br = CircuitBreaker(
            failure_threshold=threshold,
            cooldown_s=cooldown,
            now=lambda: t[0],
            on_transition=seen.append,
        )
        return br, t, seen

    def test_closed_until_threshold(self):
        br, _, seen = self._breaker(threshold=3)
        for _ in range(2):
            br.record_failure()
            assert br.state == CircuitBreaker.CLOSED and br.allow()
        br.record_failure()
        assert br.state == CircuitBreaker.OPEN
        assert not br.allow()
        assert seen == [CircuitBreaker.OPEN]

    def test_success_resets_the_failure_count(self):
        br, _, _ = self._breaker(threshold=3)
        br.record_failure()
        br.record_failure()
        br.record_success()
        br.record_failure()
        br.record_failure()
        assert br.state == CircuitBreaker.CLOSED  # 2 < 3 after the reset

    def test_open_to_half_open_after_cooldown(self):
        br, t, seen = self._breaker(threshold=1, cooldown=10.0)
        br.record_failure()
        assert not br.allow()
        t[0] = 9.9
        assert not br.allow()  # still cooling
        t[0] = 10.0
        assert br.allow()  # the probe
        assert br.state == CircuitBreaker.HALF_OPEN
        assert seen == [CircuitBreaker.OPEN, CircuitBreaker.HALF_OPEN]

    def test_half_open_probe_success_closes(self):
        br, t, seen = self._breaker(threshold=1, cooldown=5.0)
        br.record_failure()
        t[0] = 5.0
        assert br.allow()
        br.record_success()
        assert br.state == CircuitBreaker.CLOSED
        assert br.allow()
        assert seen[-1] == CircuitBreaker.CLOSED

    def test_half_open_probe_failure_reopens_with_fresh_cooldown(self):
        br, t, _ = self._breaker(threshold=1, cooldown=5.0)
        br.record_failure()  # open at t=0
        t[0] = 5.0
        assert br.allow()  # half-open probe
        br.record_failure()  # probe failed
        assert br.state == CircuitBreaker.OPEN
        t[0] = 9.9  # 4.9s into the NEW cooldown — not the old one
        assert not br.allow()
        t[0] = 10.0
        assert br.allow()


class TestInjectedErrors:
    def test_injected_unavailable_classifies_transient(self):
        err = injected_rpc_error("unavailable", "chaos")
        assert isinstance(err, InjectedRpcError)
        assert is_transient_code(err)
        assert err.details() == "chaos"

    def test_non_rpc_errors_are_not_transient_codes(self):
        assert not is_transient_code(RuntimeError("nope"))
