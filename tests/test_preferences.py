"""Preference relaxation ladder (preferences.go parity)."""

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
from karpenter_tpu.controllers.provisioning.preferences import (
    RUNG_TOLERATE,
    can_relax,
    relax_pod,
    rungs,
)
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import (
    NodeAffinity,
    NodeSelectorTerm,
    PreferredSchedulingTerm,
    TopologySpreadConstraint,
    make_pod,
)
from karpenter_tpu.models.taints import NO_SCHEDULE, PREFER_NO_SCHEDULE, Taint


def default_pool(name="default", taints=()):
    pool = NodePool()
    pool.metadata.name = name
    pool.spec.template.spec.taints = list(taints)
    return pool


class TestRelaxPod:
    def test_preferred_affinity_dropped_first(self):
        pod = make_pod("p")
        pod.spec.node_affinity = NodeAffinity(
            preferred=[PreferredSchedulingTerm(1, [{"key": "x", "operator": "In", "values": ["a"]}])]
        )
        assert can_relax(pod, 0)
        relaxed = relax_pod(pod, 1)
        assert relaxed.spec.node_affinity.preferred == []
        assert pod.spec.node_affinity.preferred  # original untouched
        assert relaxed.uid == pod.uid

    def test_required_or_terms_advance_one_per_rung(self):
        pod = make_pod("p")
        pod.spec.node_affinity = NodeAffinity(
            required=[
                NodeSelectorTerm([{"key": "zone", "operator": "In", "values": ["nowhere-1"]}]),
                NodeSelectorTerm([{"key": "zone", "operator": "In", "values": ["nowhere-2"]}]),
                NodeSelectorTerm([{"key": "zone", "operator": "In", "values": ["test-zone-1"]}]),
            ]
        )
        # ladder: two or-term rungs then the toleration rung
        assert rungs(pod)[:2] == ["required-or-term", "required-or-term"]
        one = relax_pod(pod, 1)
        assert one.spec.node_affinity.required[0].match_expressions[0]["values"] == ["nowhere-2"]
        two = relax_pod(pod, 2)
        assert two.spec.node_affinity.required[0].match_expressions[0]["values"] == [
            "test-zone-1"
        ]

    def test_schedule_anyway_tsc_dropped(self):
        pod = make_pod("p")
        pod.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                topology_key=l.LABEL_TOPOLOGY_ZONE,
                when_unsatisfiable="ScheduleAnyway",
                label_selector={"a": "b"},
            )
        ]
        assert rungs(pod) == ["schedule-anyway-tsc", RUNG_TOLERATE]
        assert relax_pod(pod, 1).spec.topology_spread_constraints == []

    def test_prefer_no_schedule_toleration_last(self):
        pod = make_pod("p")
        assert rungs(pod) == [RUNG_TOLERATE]
        relaxed = relax_pod(pod, 1)
        assert any(t.effect == PREFER_NO_SCHEDULE for t in relaxed.spec.tolerations)
        assert not can_relax(pod, 1)


class TestLadderEndToEnd:
    def test_unsatisfiable_preferred_affinity_still_schedules(self):
        templates = build_templates([(default_pool(), instance_types(16))])
        pod = make_pod("p", cpu=0.5)
        pod.spec.node_affinity = NodeAffinity(
            preferred=[
                PreferredSchedulingTerm(
                    10, [{"key": l.LABEL_TOPOLOGY_ZONE, "operator": "In", "values": ["zone-nowhere"]}]
                )
            ]
        )
        result = TPUScheduler(templates).solve([pod])
        assert not result.unschedulable
        # the preference was shed: the claim is launchable on a real offering
        it, price = result.claims[0].cheapest_launch()
        assert it is not None and price < float("inf")

    def test_or_terms_fall_through(self):
        templates = build_templates([(default_pool(), instance_types(16))])
        pod = make_pod("p", cpu=0.5)
        pod.spec.node_affinity = NodeAffinity(
            required=[
                NodeSelectorTerm(
                    [{"key": l.LABEL_TOPOLOGY_ZONE, "operator": "In", "values": ["zone-nowhere"]}]
                ),
                NodeSelectorTerm(
                    [{"key": l.LABEL_TOPOLOGY_ZONE, "operator": "In", "values": ["test-zone-2"]}]
                ),
            ]
        )
        result = TPUScheduler(templates).solve([pod])
        assert not result.unschedulable
        assert sorted(result.claims[0].requirements.get(l.LABEL_TOPOLOGY_ZONE).values) == [
            "test-zone-2"
        ]

    def test_three_or_terms_fall_through(self):
        """One term is shed per round, so the THIRD OR term is reachable."""
        templates = build_templates([(default_pool(), instance_types(16))])
        pod = make_pod("p", cpu=0.5)
        pod.spec.node_affinity = NodeAffinity(
            required=[
                NodeSelectorTerm(
                    [{"key": l.LABEL_TOPOLOGY_ZONE, "operator": "In", "values": ["zone-nowhere-1"]}]
                ),
                NodeSelectorTerm(
                    [{"key": l.LABEL_TOPOLOGY_ZONE, "operator": "In", "values": ["zone-nowhere-2"]}]
                ),
                NodeSelectorTerm(
                    [{"key": l.LABEL_TOPOLOGY_ZONE, "operator": "In", "values": ["test-zone-2"]}]
                ),
            ]
        )
        result = TPUScheduler(templates).solve([pod])
        assert not result.unschedulable
        assert sorted(result.claims[0].requirements.get(l.LABEL_TOPOLOGY_ZONE).values) == [
            "test-zone-2"
        ]

    def test_schedule_anyway_spreads_when_possible(self):
        """Soft TSCs spread while capacity allows."""
        templates = build_templates([(default_pool(), instance_types(32))])
        pods = []
        for i in range(8):
            p = make_pod(f"p-{i}", cpu=0.5)
            p.metadata.labels = {"app": "soft"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=l.LABEL_TOPOLOGY_ZONE,
                    when_unsatisfiable="ScheduleAnyway",
                    label_selector={"app": "soft"},
                )
            ]
            pods.append(p)
        result = TPUScheduler(templates).solve(pods)
        assert not result.unschedulable
        zones = {}
        for c in result.claims:
            z = sorted(c.requirements.get(l.LABEL_TOPOLOGY_ZONE).values)[0]
            zones[z] = zones.get(z, 0) + len(c.pods)
        assert max(zones.values()) - min(zones.values()) <= 1

    def test_schedule_anyway_violated_when_necessary(self):
        """A one-zone pool can't spread; soft TSC pods must still schedule."""
        pool = default_pool()
        pool.spec.template.spec.requirements = [
            {"key": l.LABEL_TOPOLOGY_ZONE, "operator": "In", "values": ["test-zone-1"]}
        ]
        templates = build_templates([(pool, instance_types(32))])
        pods = []
        for i in range(4):
            p = make_pod(f"p-{i}", cpu=0.5)
            p.metadata.labels = {"app": "soft"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=l.LABEL_TOPOLOGY_ZONE,
                    when_unsatisfiable="ScheduleAnyway",
                    label_selector={"app": "soft"},
                )
            ]
            pods.append(p)
        result = TPUScheduler(templates).solve(pods)
        assert not result.unschedulable

    def test_prefer_no_schedule_tolerated_as_last_resort(self):
        taint = Taint(key="soft-keep-off", effect=PREFER_NO_SCHEDULE)
        templates = build_templates([(default_pool(taints=[taint]), instance_types(16))])
        pod = make_pod("p", cpu=0.5)
        result = TPUScheduler(templates).solve([pod])
        assert not result.unschedulable

    def test_host_and_device_agree_on_soft_tsc_rescue(self):
        """Both engines run the ladder: a soft-TSC pod that cannot spread
        (counts seeded in an unreachable zone) still schedules on BOTH."""
        from karpenter_tpu.controllers.provisioning import HostScheduler
        from karpenter_tpu.controllers.provisioning.topology import (
            Topology,
            build_universe_domains,
        )

        pool = default_pool()
        pool.spec.template.spec.requirements = [
            {"key": l.LABEL_TOPOLOGY_ZONE, "operator": "In", "values": ["test-zone-1"]}
        ]
        templates = build_templates([(pool, instance_types(32))])

        def mk_pod():
            p = make_pod("p", cpu=0.5)
            p.metadata.labels = {"app": "soft"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=l.LABEL_TOPOLOGY_ZONE,
                    when_unsatisfiable="ScheduleAnyway",
                    label_selector={"app": "soft"},
                )
            ]
            return p

        # seed counts: two app=soft pods already bound in a zone this pool
        # cannot reach, making the spread unsatisfiable
        universe = dict(build_universe_domains(templates))
        universe[l.LABEL_TOPOLOGY_ZONE] = {"test-zone-1", "test-zone-2"}
        bound = []
        for i in range(2):
            bp = make_pod(f"bound-{i}")
            bp.metadata.labels = {"app": "soft"}
            bp.spec.topology_spread_constraints = mk_pod().spec.topology_spread_constraints
            bound.append((bp, {l.LABEL_TOPOLOGY_ZONE: "test-zone-2"}))

        pod_h = mk_pod()
        topo_h = Topology.build([pod_h] + [b for b, _ in bound], universe, bound)
        host = HostScheduler(templates, topology=topo_h).solve([pod_h])
        assert not host.unschedulable, "host ladder failed to rescue soft TSC"

        pod_t = mk_pod()
        topo_t = Topology.build([pod_t] + [b for b, _ in bound], universe, bound)
        tpu = TPUScheduler(templates).solve([pod_t], topology=topo_t)
        assert not tpu.unschedulable, "device ladder failed to rescue soft TSC"

    def test_hard_constraints_never_relaxed(self):
        taint = Taint(key="dedicated", value="x", effect=NO_SCHEDULE)
        templates = build_templates([(default_pool(taints=[taint]), instance_types(16))])
        pod = make_pod("p", cpu=0.5)
        result = TPUScheduler(templates).solve([pod])
        assert len(result.unschedulable) == 1
