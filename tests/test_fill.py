"""Differential tests for the kind-level batch placement path (solve_fill).

Every case runs the SAME workload through the TPU engine (which routes
batchable kinds through the fill scan) and the per-pod host oracle, then
compares pod->slot assignments, claim pod lists, viable instance types and
node counts. Workloads use f32-product-exact quantities (powers of two)
so the fill kernel's one-multiply-add accumulation is bit-identical to
the oracle's sequential merge (see ops/solver.py batch placement notes).

Reference parity: scheduler.go:582-612 (3-tier cascade), queue.go:72-90
(FFD order), topologygroup.go:229+ (hostname spread min=0 semantics).
"""

from __future__ import annotations

import numpy as np
import pytest

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
from karpenter_tpu.controllers.provisioning.host_scheduler import (
    ExistingSimNode,
    HostScheduler,
)
from karpenter_tpu.controllers.provisioning.topology import (
    Topology,
    build_universe_domains,
)
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import (
    HostPort,
    PodAffinityTerm,
    TopologySpreadConstraint,
    make_pod,
)
from karpenter_tpu.scheduling import Operator, Requirement, Requirements


def _templates(n_types=20):
    pool = NodePool()
    pool.metadata.name = "default"
    return build_templates([(pool, instance_types(n_types))])


def _compare(templates, pods, existing=None, max_claims=64, expect_unschedulable=0):
    """Run both engines and assert identical packings."""
    sched = TPUScheduler(templates, max_claims=max_claims)
    stats = {"fill": 0, "pods": 0, "kscan": 0}
    orig = sched._run_solve_inner

    def wrapped(enc):
        state, outputs, tmpl_snaps = orig(enc)
        for o in outputs:
            stats[o[0]] += 1
        return state, outputs, tmpl_snaps

    sched._run_solve_inner = wrapped
    r_dev = sched.solve(pods, existing_nodes=[n.clone() for n in (existing or [])])
    universe = build_universe_domains(templates, existing or [])
    host = HostScheduler(
        templates,
        existing_nodes=[n.clone() for n in (existing or [])],
        topology=Topology.build(list(pods), universe),
    )
    r_host = host.solve(list(pods))
    assert len(r_dev.claims) == len(r_host.claims)
    for cd, ch in zip(r_dev.claims, r_host.claims):
        assert [p.uid for p in cd.pods] == [p.uid for p in ch.pods]
        assert sorted(i.name for i in cd.instance_types) == sorted(
            i.name for i in ch.instance_types
        )
        assert cd.used == ch.used, (cd.slot, cd.used, ch.used)
        assert cd.hostname == ch.hostname
    assert r_dev.assignments == r_host.assignments
    assert r_dev.existing_assignments == r_host.existing_assignments
    assert [p.uid for p, _ in r_dev.unschedulable] == [
        p.uid for p, _ in r_host.unschedulable
    ]
    assert len(r_dev.unschedulable) == expect_unschedulable
    return r_dev, stats


def _pods(n, cpu=0.5, mem="1Gi", prefix="p", **kw):
    return [make_pod(f"{prefix}-{i}", cpu=cpu, memory=mem, **kw) for i in range(n)]


class TestFillParity:
    def test_identical_pods_pack(self):
        tmpl = _templates()
        r, stats = _compare(tmpl, _pods(64))
        assert stats["fill"] >= 1 and stats["pods"] == 0
        assert r.node_count >= 1

    def test_two_kinds_water_fill(self):
        # big pods open claims; small pods water-fill the remainders
        tmpl = _templates()
        pods = _pods(8, cpu=2.0, mem="4Gi", prefix="big") + _pods(
            40, cpu=0.25, mem="256Mi", prefix="small"
        )
        r, stats = _compare(tmpl, pods)
        assert stats["fill"] >= 1 and stats["pods"] == 0

    def test_selector_kinds(self):
        tmpl = _templates()
        pods = []
        zones = ("test-zone-1", "test-zone-2")
        for i in range(48):
            sel = {}
            if i % 3 == 1:
                sel[l.LABEL_TOPOLOGY_ZONE] = zones[i % 2]
            if i % 3 == 2:
                sel[l.CAPACITY_TYPE_LABEL_KEY] = l.CAPACITY_TYPE_ON_DEMAND
            pods.append(make_pod(f"s-{i}", cpu=0.5, memory="1Gi", node_selector=sel))
        _compare(tmpl, pods)

    def test_existing_nodes_tier1_fill(self):
        tmpl = _templates()
        reqs = Requirements()
        reqs.add(Requirement.new(l.LABEL_HOSTNAME, Operator.IN, "node-a"))
        reqs.add(Requirement.new(l.LABEL_TOPOLOGY_ZONE, Operator.IN, "test-zone-1"))
        reqs.add(
            Requirement.new(l.CAPACITY_TYPE_LABEL_KEY, Operator.IN, l.CAPACITY_TYPE_ON_DEMAND)
        )
        node = ExistingSimNode(
            name="node-a",
            index=0,
            requirements=reqs,
            available={"cpu": 4.0, "memory": float(8 * 2**30), "pods": 110.0},
        )
        # 8 pods of 0.5 cpu: node takes 8; 16 more overflow to new claims
        r, stats = _compare(tmpl, _pods(24, cpu=0.5, mem="512Mi"), existing=[node])
        assert stats["fill"] >= 1
        assert len(r.existing_assignments) == 8

    def test_hostname_spread_one_per_node(self):
        tmpl = _templates()
        pods = []
        for i in range(12):
            p = make_pod(f"h-{i}", cpu=0.25, memory="256Mi")
            p.metadata.labels = {"spread": "host"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=l.LABEL_HOSTNAME,
                    label_selector={"spread": "host"},
                )
            ]
            pods.append(p)
        r, stats = _compare(tmpl, pods)
        assert stats["fill"] >= 1 and stats["pods"] == 0  # hg kinds batch
        assert r.node_count == 12  # maxSkew=1, fresh domain always at 0

    def test_hostname_spread_skew2(self):
        tmpl = _templates()
        pods = []
        for i in range(12):
            p = make_pod(f"h2-{i}", cpu=0.25, memory="256Mi")
            p.metadata.labels = {"spread": "host2"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=2,
                    topology_key=l.LABEL_HOSTNAME,
                    label_selector={"spread": "host2"},
                )
            ]
            pods.append(p)
        r, stats = _compare(tmpl, pods)
        assert stats["fill"] >= 1
        assert r.node_count == 6  # two per node at skew 2

    def test_anti_affinity_one_per_node(self):
        tmpl = _templates()
        pods = []
        for i in range(10):
            p = make_pod(f"a-{i}", cpu=0.25, memory="256Mi")
            p.metadata.labels = {"app": "nginx"}
            p.spec.pod_anti_affinity = [
                PodAffinityTerm(
                    topology_key=l.LABEL_HOSTNAME, label_selector={"app": "nginx"}
                )
            ]
            pods.append(p)
        r, stats = _compare(tmpl, pods)
        assert stats["fill"] >= 1 and stats["pods"] == 0
        assert r.node_count == 10

    def test_hostport_self_conflict_one_per_node(self):
        tmpl = _templates()
        pods = _pods(6, cpu=0.25, mem="256Mi", host_ports=[HostPort(port=8080)])
        r, stats = _compare(tmpl, pods)
        assert stats["fill"] >= 1
        assert r.node_count == 6

    def test_no_claim_impossible_selector(self):
        tmpl = _templates()
        pods = _pods(5, node_selector={l.LABEL_TOPOLOGY_ZONE: "nonexistent-zone"})
        _compare(tmpl, pods, expect_unschedulable=5)

    def test_no_room_recovers_to_host_packing(self):
        # NO_ROOM is a device-shape artifact with no reference analog: the
        # Go scheduler always opens another node (scheduler.go:582-612).
        # With max_claims=4 and 8 pods that each need their own claim, the
        # solver must double its slot capacity and re-solve until it
        # reproduces the host packing — never fail pods on a shape limit.
        tmpl = _templates(1)  # single 1-cpu type (alloc ~0.918 cpu)
        pods = _pods(8, cpu=0.5, mem="256Mi")
        r, stats = _compare(tmpl, pods, max_claims=4, expect_unschedulable=0)
        assert len(r.claims) == 8

    def test_vg_kinds_interleave_with_fill(self):
        # zonal TSC pods (per-pod scan) interleaved with identical generic
        # pods (fill scan) at the same FFD size
        tmpl = _templates()
        pods = []
        for i in range(30):
            p = make_pod(f"m-{i}", cpu=0.5, memory="1Gi")
            if i % 2 == 0:
                p.metadata.labels = {"spread": "zonal"}
                p.spec.topology_spread_constraints = [
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=l.LABEL_TOPOLOGY_ZONE,
                        label_selector={"spread": "zonal"},
                    )
                ]
            pods.append(p)
        r, stats = _compare(tmpl, pods)
        # single-key zonal kinds now ride the kind scan, not the per-pod
        # scan (ops/solver.py solve_kind_scan)
        assert stats["fill"] >= 1 and stats["kscan"] >= 1

    def test_fill_then_per_pod_lands_on_fill_claims(self):
        # generic pods open claims via fill; a later zonal-TSC kind (same
        # size class ordering puts it after) must still see those claims
        tmpl = _templates()
        pods = _pods(16, cpu=1.0, mem="1Gi", prefix="g")
        for i in range(4):
            p = make_pod(f"z-{i}", cpu=0.5, memory="512Mi")
            p.metadata.labels = {"spread": "zonal"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=l.LABEL_TOPOLOGY_ZONE,
                    label_selector={"spread": "zonal"},
                )
            ]
            pods.append(p)
        _compare(tmpl, pods)


class TestFillUnits:
    def test_water_fill_matches_bruteforce(self):
        import jax.numpy as jnp

        from karpenter_tpu.ops.solver import _water_fill

        rng = np.random.default_rng(7)
        for _ in range(50):
            n = 16
            p = rng.integers(0, 6, n).astype(np.int32)
            f = rng.integers(0, 5, n).astype(np.int32)
            rem = int(rng.integers(0, 25))
            got = np.asarray(_water_fill(jnp.asarray(p), jnp.asarray(f), jnp.int32(rem)))
            # brute force: repeatedly place on argmin (count, slot) with capacity
            cnt = p.copy()
            cap = f.copy()
            fill = np.zeros(n, dtype=np.int32)
            for _ in range(rem):
                cands = np.flatnonzero(cap > 0)
                if len(cands) == 0:
                    break
                j = cands[np.lexsort((cands, cnt[cands]))[0]]
                fill[j] += 1
                cnt[j] += 1
                cap[j] -= 1
            assert (got == fill).all(), (p, f, rem, got, fill)

    def test_count_cap_product_convention(self):
        import jax.numpy as jnp

        from karpenter_tpu.ops.solver import _count_cap_seq

        used = jnp.asarray([[0.0, 0.0], [1.0, 0.0]], dtype=jnp.float32)
        req = jnp.asarray([0.5, 0.0], dtype=jnp.float32)
        limit = jnp.asarray([[4.0, 1.0], [4.0, 1.0]], dtype=jnp.float32)
        got = np.asarray(_count_cap_seq(used, req[None, :], limit))
        assert got.tolist() == [8, 6]


class TestCrossConventionFloats:
    """VERDICT r3 weak #9: quantities that are NOT f32-product-exact
    (0.3 CPU) crossing the two accumulation conventions — the fill
    kernel's one-multiply-add (used + c*req) vs the per-pod engines'
    sequential merge — must never produce divergent PLACEMENTS, phantom
    unschedulables, or infeasibility when one engine's claims feed the
    other engine's tier-1 path."""

    def test_non_exact_quantities_place_identically(self):
        tmpl = _templates()
        pods = _pods(9, cpu=0.3, mem="300Mi")
        sched = TPUScheduler(tmpl, max_claims=16)
        r_dev = sched.solve(pods)
        universe = build_universe_domains(tmpl, [])
        host = HostScheduler(tmpl, topology=Topology.build(list(pods), universe))
        r_host = host.solve(list(pods))
        assert not r_dev.unschedulable and not r_host.unschedulable
        assert r_dev.assignments == r_host.assignments
        assert len(r_dev.claims) == len(r_host.claims)
        for cd, ch in zip(r_dev.claims, r_host.claims):
            assert [p.uid for p in cd.pods] == [p.uid for p in ch.pods]
            # used may differ in ulps across conventions — never more
            for k in set(cd.used) | set(ch.used):
                assert abs(cd.used.get(k, 0.0) - ch.used.get(k, 0.0)) <= max(
                    1e-4, 1e-6 * abs(ch.used.get(k, 0.0))
                ), (k, cd.used, ch.used)

    def test_fill_claims_replay_through_per_pod_tier1(self):
        """Claims opened by the fill kernel become existing nodes (the
        post-launch cluster state); MORE non-exact pods then solve against
        that f32 usage on BOTH engines — the consolidation-what-if shape
        of the cross-convention risk."""
        tmpl = _templates()
        first = _pods(6, cpu=0.3, mem="256Mi")
        sched = TPUScheduler(tmpl, max_claims=16)
        r = sched.solve(first)
        assert not r.unschedulable
        existing = []
        for c in r.claims:
            it, _price = c.cheapest_launch()
            alloc = it.allocatable()
            avail = {k: alloc.get(k, 0.0) - c.used.get(k, 0.0) for k in alloc}
            existing.append(
                ExistingSimNode(
                    name=f"node-{c.slot}",
                    index=len(existing),
                    requirements=Requirements.from_labels(
                        {
                            l.LABEL_INSTANCE_TYPE: it.name,
                            l.LABEL_TOPOLOGY_ZONE: it.offerings[0].zone,
                            l.CAPACITY_TYPE_LABEL_KEY: it.offerings[0].capacity_type,
                            l.LABEL_ARCH: "amd64",
                            l.LABEL_HOSTNAME: f"node-{c.slot}",
                        }
                    ),
                    available=avail,
                )
            )
        second = _pods(4, cpu=0.3, mem="256Mi", prefix="q")
        _compare(tmpl, second, existing=existing)
