"""Differential tests: the TPU solver must pack exactly like the
exact-semantics host oracle (claim counts, assignments, viable type sets)."""

import numpy as np
import pytest

from karpenter_tpu.cloudprovider.fake import instance_types, new_instance_type
from karpenter_tpu.controllers.provisioning import (
    HostScheduler,
    TPUScheduler,
    build_templates,
)
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.models.taints import NO_SCHEDULE, Taint, Toleration
from karpenter_tpu.utils import resources as res


def default_pool(name="default", weight=0, requirements=(), taints=()):
    pool = NodePool()
    pool.metadata.name = name
    pool.spec.weight = weight
    pool.spec.template.spec.requirements = list(requirements)
    pool.spec.template.spec.taints = list(taints)
    return pool


def random_pods(rng, n, zones=("test-zone-1", "test-zone-2"), selector_rate=0.3):
    pods = []
    for i in range(n):
        cpu = float(rng.choice([0.1, 0.25, 0.5, 1.0, 2.0, 4.0]))
        mem_gi = float(rng.choice([0.25, 0.5, 1.0, 2.0, 8.0]))
        sel = {}
        if rng.random() < selector_rate:
            sel[l.LABEL_TOPOLOGY_ZONE] = str(rng.choice(zones))
        if rng.random() < 0.2:
            sel[l.LABEL_ARCH] = l.ARCH_AMD64
        pods.append(make_pod(f"p-{i}", cpu=cpu, memory=f"{mem_gi}Gi", node_selector=sel))
    return pods


def assert_same_packing(host_result, tpu_result):
    assert len(tpu_result.claims) == len(host_result.claims)
    assert len(tpu_result.unschedulable) == len(host_result.unschedulable)
    host_by_slot = {c.slot: c for c in host_result.claims}
    tpu_by_slot = {c.slot: c for c in tpu_result.claims}
    assert host_result.assignments == tpu_result.assignments
    for slot, hc in host_by_slot.items():
        tc = tpu_by_slot[slot]
        assert [p.uid for p in hc.pods] == [p.uid for p in tc.pods]
        assert {it.name for it in hc.instance_types} == {it.name for it in tc.instance_types}
        assert hc.template.nodepool_name == tc.template.nodepool_name
        for k, v in hc.used.items():
            assert tc.used.get(k, 0.0) == pytest.approx(v)


class TestDifferential:
    def test_simple_homogeneous(self):
        pods = [make_pod(f"p-{i}", cpu=1.0, memory="1Gi") for i in range(40)]
        templates = build_templates([(default_pool(), instance_types(12))])
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        assert len(host.claims) >= 1
        assert not host.unschedulable

    def test_random_mixed(self):
        rng = np.random.default_rng(7)
        pods = random_pods(rng, 120)
        templates = build_templates([(default_pool(), instance_types(24))])
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)

    def test_multiple_pools_weight_order(self):
        rng = np.random.default_rng(3)
        pods = random_pods(rng, 60)
        catalog = instance_types(16)
        heavy = default_pool(
            "heavy",
            weight=50,
            requirements=[{"key": l.LABEL_ARCH, "operator": "In", "values": [l.ARCH_AMD64]}],
        )
        light = default_pool("light", weight=1)
        templates = build_templates([(light, catalog), (heavy, catalog)])
        assert templates[0].nodepool_name == "heavy"
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        # amd64-compatible pods should prefer the heavy pool
        assert any(c.template.nodepool_name == "heavy" for c in host.claims)

    def test_taints_and_tolerations(self):
        taint = Taint(key="dedicated", value="gpu", effect=NO_SCHEDULE)
        tainted = default_pool("tainted", weight=10, taints=[taint])
        open_pool = default_pool("open")
        catalog = instance_types(8)
        templates = build_templates([(tainted, catalog), (open_pool, catalog)])
        tolerant = make_pod("tolerant", cpu=1)
        tolerant.spec.tolerations = [Toleration(key="dedicated", operator="Equal", value="gpu")]
        intolerant = make_pod("intolerant", cpu=1)
        pods = [tolerant, intolerant]
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        # intolerant pod must land on the open pool
        for c in host.claims:
            if any(p.uid == intolerant.uid for p in c.pods):
                assert c.template.nodepool_name == "open"

    def test_unschedulable_pod(self):
        pods = [make_pod("huge", cpu=10000.0)]
        templates = build_templates([(default_pool(), instance_types(8))])
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        assert len(host.unschedulable) == 1

    def test_zone_selector_constrains_offerings(self):
        # zone-5 exists as a label value nowhere in the catalog
        pods = [make_pod("p", node_selector={l.LABEL_TOPOLOGY_ZONE: "zone-nowhere"})]
        templates = build_templates([(default_pool(), instance_types(8))])
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        assert len(host.unschedulable) == 1

    def test_nodepool_requirement_restricts_zone(self):
        pool = default_pool(
            "zonal",
            requirements=[
                {"key": l.LABEL_TOPOLOGY_ZONE, "operator": "In", "values": ["test-zone-3"]}
            ],
        )
        pods = [make_pod(f"p-{i}", cpu=1.0) for i in range(10)]
        templates = build_templates([(pool, instance_types(8))])
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        for c in tpu.claims:
            it, price = c.cheapest_launch()
            assert it is not None
            # the launchable offering must be in test-zone-3
            assert c.requirements.get(l.LABEL_TOPOLOGY_ZONE).has("test-zone-3")

    def test_ffd_order_is_stable(self):
        pods = [make_pod(f"p-{i}", cpu=1.0) for i in range(8)]
        templates = build_templates([(default_pool(), instance_types(4))])
        r1 = TPUScheduler(templates).solve(pods)
        r2 = TPUScheduler(templates).solve(pods)
        assert r1.assignments == r2.assignments


class TestRegressions:
    def test_scheduler_reuse_with_vocab_growth(self):
        """A second solve() whose pods introduce new label keys/values must
        re-encode instead of crashing on shape mismatch."""
        templates = build_templates([(default_pool(), instance_types(16))])
        s = TPUScheduler(templates)
        r1 = s.solve([make_pod("a", cpu=1.0)])
        pod_b = make_pod("b", cpu=1.0, node_selector={"myteam.example.com/tier": "gold"})
        r2 = s.solve([pod_b])
        # the custom label is undefined on the catalog -> unschedulable, not a crash
        assert len(r2.unschedulable) == 1
        assert len(r1.claims) == 1

    def test_offering_without_zone_ct_requirements(self):
        """Offerings that omit zone/capacity-type requirements admit every
        (zone, ct) — parity with Requirements.Get -> Exists semantics."""
        from karpenter_tpu.cloudprovider.instancetype import InstanceType, Offering
        from karpenter_tpu.scheduling import Requirements as Rq

        bare = InstanceType(
            "bare",
            Rq(),
            [Offering(requirements=Rq(), price=1.0)],
            {res.CPU: 4.0, res.MEMORY: 8 * 2**30, res.PODS: 16.0},
        )
        templates = build_templates([(default_pool(), [bare])])
        pods = [make_pod("p", cpu=1.0)]
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        assert not tpu.unschedulable

    def test_large_gt_bound_encodes(self):
        """Gt/Lt bounds beyond int32 must clamp, not overflow."""
        pool = default_pool(
            "bounded",
            requirements=[{"key": "custom-gen", "operator": "Gt", "values": ["3000000000"]}],
        )
        templates = build_templates([(default_pool(), instance_types(4)), (pool, instance_types(4))])
        tpu = TPUScheduler(templates).solve([make_pod("p", cpu=0.25)])
        assert not tpu.unschedulable

    def test_claim_capacity_exhaustion_reason(self):
        """When max_claims is hit, the reason says so explicitly."""
        # 1-cpu shapes only (allocatable ~0.92): one 0.5-cpu pod per node
        pods = [make_pod(f"p-{i}", cpu=0.5) for i in range(4)]
        templates = build_templates([(default_pool(), instance_types(8))])
        s = TPUScheduler(templates, max_claims=2)
        result = s.solve(pods)
        assert len(result.claims) == 2
        reasons = [r for _, r in result.unschedulable]
        assert len(reasons) == 2 and all("capacity exhausted" in r for r in reasons)

    def test_float32_boundary_fits_parity(self):
        """Host and device agree on requests at the exact f32 allocatable
        boundary (both quantize to f32 and accumulate identically)."""
        from karpenter_tpu.cloudprovider.instancetype import InstanceType, Offering
        from karpenter_tpu.scheduling import Requirements as Rq

        weird_mem = 16731028412.16  # not f32-representable
        it = InstanceType(
            "edge",
            Rq(),
            [Offering(requirements=Rq(), price=1.0)],
            {res.CPU: 4.0, res.MEMORY: weird_mem, res.PODS: 16.0},
        )
        templates = build_templates([(default_pool(), [it])])
        pod = make_pod("p", cpu=1.0, memory=weird_mem)
        host = HostScheduler(templates).solve([pod])
        tpu = TPUScheduler(templates).solve([pod])
        assert_same_packing(host, tpu)
        # and every emitted claim has at least one viable launch type
        for c in tpu.claims:
            assert c.instance_types


class TestPackingQuality:
    def test_bin_utilization(self):
        """Packing must fill nodes densely. instance_types(64) spans cpu
        sizes 1..64 (8 shapes per size), so 64 x 1cpu pods fit in a couple
        of large nodes rather than one node per pod."""
        pods = [make_pod(f"p-{i}", cpu=1.0, memory="1Gi") for i in range(64)]
        templates = build_templates([(default_pool(), instance_types(64))])
        result = TPUScheduler(templates).solve(pods)
        assert result.node_count <= 2
        assert not result.unschedulable

    def test_dense_on_small_catalog(self):
        """With only 1/2/4-cpu shapes (instance_types(24)), 64 cores of pods
        need ~64/3.8 nodes — dense given the catalog, not one per pod."""
        pods = [make_pod(f"p-{i}", cpu=1.0, memory="1Gi") for i in range(64)]
        templates = build_templates([(default_pool(), instance_types(24))])
        result = TPUScheduler(templates).solve(pods)
        assert result.node_count <= 24
        assert not result.unschedulable
