"""Differential tests: the TPU solver must pack exactly like the
exact-semantics host oracle (claim counts, assignments, viable type sets)."""

import numpy as np
import pytest

from karpenter_tpu.cloudprovider.fake import instance_types, new_instance_type
from karpenter_tpu.controllers.provisioning import (
    HostScheduler,
    TPUScheduler,
    build_templates,
)
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import make_pod
from karpenter_tpu.models.taints import NO_SCHEDULE, Taint, Toleration
from karpenter_tpu.utils import resources as res


def default_pool(name="default", weight=0, requirements=(), taints=()):
    pool = NodePool()
    pool.metadata.name = name
    pool.spec.weight = weight
    pool.spec.template.spec.requirements = list(requirements)
    pool.spec.template.spec.taints = list(taints)
    return pool


def random_pods(rng, n, zones=("test-zone-1", "test-zone-2"), selector_rate=0.3):
    pods = []
    for i in range(n):
        cpu = float(rng.choice([0.1, 0.25, 0.5, 1.0, 2.0, 4.0]))
        mem_gi = float(rng.choice([0.25, 0.5, 1.0, 2.0, 8.0]))
        sel = {}
        if rng.random() < selector_rate:
            sel[l.LABEL_TOPOLOGY_ZONE] = str(rng.choice(zones))
        if rng.random() < 0.2:
            sel[l.LABEL_ARCH] = l.ARCH_AMD64
        pods.append(make_pod(f"p-{i}", cpu=cpu, memory=f"{mem_gi}Gi", node_selector=sel))
    return pods


def assert_same_packing(host_result, tpu_result):
    assert len(tpu_result.claims) == len(host_result.claims)
    assert len(tpu_result.unschedulable) == len(host_result.unschedulable)
    host_by_slot = {c.slot: c for c in host_result.claims}
    tpu_by_slot = {c.slot: c for c in tpu_result.claims}
    assert host_result.assignments == tpu_result.assignments
    for slot, hc in host_by_slot.items():
        tc = tpu_by_slot[slot]
        assert [p.uid for p in hc.pods] == [p.uid for p in tc.pods]
        assert {it.name for it in hc.instance_types} == {it.name for it in tc.instance_types}
        assert hc.template.nodepool_name == tc.template.nodepool_name
        for k, v in hc.used.items():
            assert tc.used.get(k, 0.0) == pytest.approx(v)


class TestDifferential:
    def test_simple_homogeneous(self):
        pods = [make_pod(f"p-{i}", cpu=1.0, memory="1Gi") for i in range(40)]
        templates = build_templates([(default_pool(), instance_types(12))])
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        assert len(host.claims) >= 1
        assert not host.unschedulable

    def test_random_mixed(self):
        rng = np.random.default_rng(7)
        pods = random_pods(rng, 120)
        templates = build_templates([(default_pool(), instance_types(24))])
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)

    def test_multiple_pools_weight_order(self):
        rng = np.random.default_rng(3)
        pods = random_pods(rng, 60)
        catalog = instance_types(16)
        heavy = default_pool(
            "heavy",
            weight=50,
            requirements=[{"key": l.LABEL_ARCH, "operator": "In", "values": [l.ARCH_AMD64]}],
        )
        light = default_pool("light", weight=1)
        templates = build_templates([(light, catalog), (heavy, catalog)])
        assert templates[0].nodepool_name == "heavy"
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        # amd64-compatible pods should prefer the heavy pool
        assert any(c.template.nodepool_name == "heavy" for c in host.claims)

    def test_taints_and_tolerations(self):
        taint = Taint(key="dedicated", value="gpu", effect=NO_SCHEDULE)
        tainted = default_pool("tainted", weight=10, taints=[taint])
        open_pool = default_pool("open")
        catalog = instance_types(8)
        templates = build_templates([(tainted, catalog), (open_pool, catalog)])
        tolerant = make_pod("tolerant", cpu=1)
        tolerant.spec.tolerations = [Toleration(key="dedicated", operator="Equal", value="gpu")]
        intolerant = make_pod("intolerant", cpu=1)
        pods = [tolerant, intolerant]
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        # intolerant pod must land on the open pool
        for c in host.claims:
            if any(p.uid == intolerant.uid for p in c.pods):
                assert c.template.nodepool_name == "open"

    def test_unschedulable_pod(self):
        pods = [make_pod("huge", cpu=10000.0)]
        templates = build_templates([(default_pool(), instance_types(8))])
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        assert len(host.unschedulable) == 1

    def test_zone_selector_constrains_offerings(self):
        # zone-5 exists as a label value nowhere in the catalog
        pods = [make_pod("p", node_selector={l.LABEL_TOPOLOGY_ZONE: "zone-nowhere"})]
        templates = build_templates([(default_pool(), instance_types(8))])
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        assert len(host.unschedulable) == 1

    def test_nodepool_requirement_restricts_zone(self):
        pool = default_pool(
            "zonal",
            requirements=[
                {"key": l.LABEL_TOPOLOGY_ZONE, "operator": "In", "values": ["test-zone-3"]}
            ],
        )
        pods = [make_pod(f"p-{i}", cpu=1.0) for i in range(10)]
        templates = build_templates([(pool, instance_types(8))])
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        for c in tpu.claims:
            it, price = c.cheapest_launch()
            assert it is not None
            # the launchable offering must be in test-zone-3
            assert c.requirements.get(l.LABEL_TOPOLOGY_ZONE).has("test-zone-3")

    def test_ffd_order_is_stable(self):
        pods = [make_pod(f"p-{i}", cpu=1.0) for i in range(8)]
        templates = build_templates([(default_pool(), instance_types(4))])
        r1 = TPUScheduler(templates).solve(pods)
        r2 = TPUScheduler(templates).solve(pods)
        assert r1.assignments == r2.assignments


def make_existing(name, index, cpu_avail=4.0, mem_avail=8 * 2**30, zone="test-zone-1",
                  it_name="s-4x-amd64", taints=()):
    from karpenter_tpu.controllers.provisioning.host_scheduler import ExistingSimNode
    from karpenter_tpu.scheduling import Requirements

    labels = {
        l.LABEL_TOPOLOGY_ZONE: zone,
        l.LABEL_INSTANCE_TYPE: it_name,
        l.CAPACITY_TYPE_LABEL_KEY: l.CAPACITY_TYPE_ON_DEMAND,
        l.LABEL_ARCH: l.ARCH_AMD64,
        l.LABEL_OS: "linux",
        l.LABEL_HOSTNAME: name,
        l.NODEPOOL_LABEL_KEY: "default",
    }
    return ExistingSimNode(
        name=name,
        index=index,
        requirements=Requirements.from_labels(labels),
        available={res.CPU: cpu_avail, res.MEMORY: float(mem_avail), res.PODS: 50.0},
        taints=list(taints),
    )


class TestExistingNodes:
    def _both(self, pods, templates, existing_factory, budgets=None):
        host = HostScheduler(templates, existing_nodes=existing_factory(), budgets=budgets).solve(pods)
        tpu = TPUScheduler(templates).solve(pods, existing_factory(), budgets)
        return host, tpu

    def test_existing_first(self):
        pods = [make_pod(f"p-{i}", cpu=0.5, memory="512Mi") for i in range(6)]
        templates = build_templates([(default_pool(), instance_types(16))])
        factory = lambda: [make_existing("node-a", 0), make_existing("node-b", 1)]
        host, tpu = self._both(pods, templates, factory)
        assert_same_packing(host, tpu)
        assert host.existing_assignments == tpu.existing_assignments
        # all six fit on the two existing nodes -> zero new claims
        assert host.node_count == 0
        assert len(host.existing_assignments) == 6

    def test_overflow_to_new_claims(self):
        pods = [make_pod(f"p-{i}", cpu=2.0, memory="1Gi") for i in range(8)]
        templates = build_templates([(default_pool(), instance_types(32))])
        factory = lambda: [make_existing("node-a", 0, cpu_avail=4.0)]
        host, tpu = self._both(pods, templates, factory)
        assert_same_packing(host, tpu)
        assert host.existing_assignments == tpu.existing_assignments
        assert len(host.existing_assignments) == 2  # 2x2cpu fit the node
        assert host.node_count >= 1

    def test_existing_node_zone_constrains(self):
        pods = [
            make_pod("z2", cpu=0.5, node_selector={l.LABEL_TOPOLOGY_ZONE: "test-zone-2"}),
            make_pod("z1", cpu=0.5, node_selector={l.LABEL_TOPOLOGY_ZONE: "test-zone-1"}),
        ]
        templates = build_templates([(default_pool(), instance_types(16))])
        factory = lambda: [make_existing("node-a", 0, zone="test-zone-1")]
        host, tpu = self._both(pods, templates, factory)
        assert_same_packing(host, tpu)
        assert host.existing_assignments == tpu.existing_assignments
        # only the zone-1 pod lands on the existing node
        assert list(host.existing_assignments.values()) == ["node-a"]

    def test_existing_node_taints(self):
        from karpenter_tpu.models.taints import NO_SCHEDULE, Taint

        taint = Taint(key="dedicated", value="x", effect=NO_SCHEDULE)
        pods = [make_pod("p", cpu=0.5)]
        templates = build_templates([(default_pool(), instance_types(16))])
        factory = lambda: [make_existing("node-a", 0, taints=[taint])]
        host, tpu = self._both(pods, templates, factory)
        assert_same_packing(host, tpu)
        assert not host.existing_assignments  # intolerant pod skips the node

    def test_hostname_selector_targets_existing(self):
        pods = [make_pod("p", cpu=0.5, node_selector={l.LABEL_HOSTNAME: "node-b"})]
        templates = build_templates([(default_pool(), instance_types(16))])
        factory = lambda: [make_existing("node-a", 0), make_existing("node-b", 1)]
        host, tpu = self._both(pods, templates, factory)
        assert host.existing_assignments == tpu.existing_assignments == {pods[0].uid: "node-b"}

    def test_instance_type_selector_vs_existing(self):
        pods = [make_pod("p", cpu=0.5, node_selector={l.LABEL_INSTANCE_TYPE: "c-1x-amd64"})]
        templates = build_templates([(default_pool(), instance_types(16))])
        factory = lambda: [make_existing("node-a", 0, it_name="s-4x-amd64")]
        host, tpu = self._both(pods, templates, factory)
        assert_same_packing(host, tpu)
        assert not host.existing_assignments  # wrong instance type
        assert host.node_count == 1  # lands on a new c-1x-amd64 claim


class TestLimits:
    def test_node_count_limit(self):
        from karpenter_tpu.models.nodepool import Limits

        pool = default_pool()
        pool.spec.limits = Limits(resources={"nodes": 2})
        pods = [make_pod(f"p-{i}", cpu=0.5) for i in range(40)]
        templates = build_templates([(pool, instance_types(8))])  # 1-cpu shapes
        budgets = {"default": {"nodes": 2.0}}
        host = HostScheduler(templates, budgets=budgets).solve(pods)
        tpu = TPUScheduler(templates).solve(pods, budgets=budgets)
        assert_same_packing(host, tpu)
        assert host.node_count == 2
        assert len(host.unschedulable) > 0

    def test_cpu_limit_filters_instance_types(self):
        pods = [make_pod(f"p-{i}", cpu=0.5) for i in range(4)]
        templates = build_templates([(default_pool(), instance_types(64))])
        # only 1-cpu and 2-cpu shapes fit a 2-cpu remaining budget
        budgets = {"default": {res.CPU: 2.0}}
        host = HostScheduler(templates, budgets=budgets).solve(pods)
        tpu = TPUScheduler(templates).solve(pods, budgets=budgets)
        assert_same_packing(host, tpu)
        for c in host.claims:
            assert all(it.capacity[res.CPU] <= 2.0 for it in c.instance_types)

    def test_unlimited_pool_unaffected(self):
        pods = [make_pod(f"p-{i}", cpu=0.5) for i in range(10)]
        templates = build_templates([(default_pool(), instance_types(16))])
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        assert not host.unschedulable


class TestRegressions:
    def test_scheduler_reuse_with_vocab_growth(self):
        """A second solve() whose pods introduce new label keys/values must
        re-encode instead of crashing on shape mismatch."""
        templates = build_templates([(default_pool(), instance_types(16))])
        s = TPUScheduler(templates)
        r1 = s.solve([make_pod("a", cpu=1.0)])
        pod_b = make_pod("b", cpu=1.0, node_selector={"myteam.example.com/tier": "gold"})
        r2 = s.solve([pod_b])
        # the custom label is undefined on the catalog -> unschedulable, not a crash
        assert len(r2.unschedulable) == 1
        assert len(r1.claims) == 1

    def test_offering_without_zone_ct_requirements(self):
        """Offerings that omit zone/capacity-type requirements admit every
        (zone, ct) — parity with Requirements.Get -> Exists semantics."""
        from karpenter_tpu.cloudprovider.instancetype import InstanceType, Offering
        from karpenter_tpu.scheduling import Requirements as Rq

        bare = InstanceType(
            "bare",
            Rq(),
            [Offering(requirements=Rq(), price=1.0)],
            {res.CPU: 4.0, res.MEMORY: 8 * 2**30, res.PODS: 16.0},
        )
        templates = build_templates([(default_pool(), [bare])])
        pods = [make_pod("p", cpu=1.0)]
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        assert not tpu.unschedulable

    def test_large_gt_bound_encodes(self):
        """Gt/Lt bounds beyond int32 must clamp, not overflow."""
        pool = default_pool(
            "bounded",
            requirements=[{"key": "custom-gen", "operator": "Gt", "values": ["3000000000"]}],
        )
        templates = build_templates([(default_pool(), instance_types(4)), (pool, instance_types(4))])
        tpu = TPUScheduler(templates).solve([make_pod("p", cpu=0.25)])
        assert not tpu.unschedulable

    def test_claim_capacity_exhaustion_recovers(self):
        """Hitting max_claims doubles the slot capacity and re-solves —
        the reference never fails a pod because the solver ran out of
        claim slots (scheduler.go:582-612 always opens another node)."""
        # 1-cpu shapes only (allocatable ~0.92): one 0.5-cpu pod per node
        pods = [make_pod(f"p-{i}", cpu=0.5) for i in range(4)]
        templates = build_templates([(default_pool(), instance_types(8))])
        s = TPUScheduler(templates, max_claims=2)
        result = s.solve(pods)
        assert len(result.claims) == 4
        assert not result.unschedulable

    def test_float32_boundary_fits_parity(self):
        """Host and device agree on requests at the exact f32 allocatable
        boundary (both quantize to f32 and accumulate identically)."""
        from karpenter_tpu.cloudprovider.instancetype import InstanceType, Offering
        from karpenter_tpu.scheduling import Requirements as Rq

        weird_mem = 16731028412.16  # not f32-representable
        it = InstanceType(
            "edge",
            Rq(),
            [Offering(requirements=Rq(), price=1.0)],
            {res.CPU: 4.0, res.MEMORY: weird_mem, res.PODS: 16.0},
        )
        templates = build_templates([(default_pool(), [it])])
        pod = make_pod("p", cpu=1.0, memory=weird_mem)
        host = HostScheduler(templates).solve([pod])
        tpu = TPUScheduler(templates).solve([pod])
        assert_same_packing(host, tpu)
        # and every emitted claim has at least one viable launch type
        for c in tpu.claims:
            assert c.instance_types


class TestTopologyDifferential:
    """The device engine must match the host oracle with topology groups in
    play (the hard order-dependent case)."""

    def _both(self, pods, n_types=32, existing_factory=None):
        from karpenter_tpu.controllers.provisioning.topology import (
            Topology,
            build_universe_domains,
        )

        templates = build_templates([(default_pool(), instance_types(n_types))])
        existing = existing_factory() if existing_factory else []
        universe = build_universe_domains(templates, existing)
        host = HostScheduler(
            templates,
            existing_nodes=existing_factory() if existing_factory else [],
            topology=Topology.build(pods, universe),
        ).solve(pods)
        tpu = TPUScheduler(templates).solve(
            pods,
            existing_factory() if existing_factory else [],
            topology=Topology.build(pods, universe),
        )
        return host, tpu

    def _spread_pods(self, n, key, max_skew=1, cpu=0.5):
        from karpenter_tpu.models.pod import TopologySpreadConstraint

        pods = []
        for i in range(n):
            p = make_pod(f"sp-{i}", cpu=cpu)
            p.metadata.labels = {"app": "web"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=max_skew, topology_key=key, label_selector={"app": "web"}
                )
            ]
            pods.append(p)
        return pods

    def test_zonal_spread_matches(self):
        pods = self._spread_pods(12, l.LABEL_TOPOLOGY_ZONE)
        host, tpu = self._both(pods)
        assert_same_packing(host, tpu)
        assert not tpu.unschedulable
        # and the packing actually spreads
        zones = {}
        for c in tpu.claims:
            z = sorted(c.requirements.get(l.LABEL_TOPOLOGY_ZONE).values)[0]
            zones[z] = zones.get(z, 0) + len(c.pods)
        assert max(zones.values()) - min(zones.values()) <= 1

    def test_hostname_spread_matches(self):
        pods = self._spread_pods(6, l.LABEL_HOSTNAME)
        host, tpu = self._both(pods, n_types=64)
        assert_same_packing(host, tpu)
        assert len(tpu.claims) == 6  # one matching pod per fresh node

    def test_anti_affinity_matches(self):
        from karpenter_tpu.models.pod import PodAffinityTerm

        pods = []
        for i, zone in enumerate(["test-zone-1", "test-zone-2", "test-zone-3"]):
            p = make_pod(f"aa-{i}", cpu=2.0, node_selector={l.LABEL_TOPOLOGY_ZONE: zone})
            p.metadata.labels = {"security": "s2"}
            pods.append(p)
        aff = make_pod("aff", cpu=0.25)
        aff.spec.pod_anti_affinity = [
            PodAffinityTerm(topology_key=l.LABEL_TOPOLOGY_ZONE, label_selector={"security": "s2"})
        ]
        host, tpu = self._both(pods + [aff])
        assert_same_packing(host, tpu)

    def test_hostname_anti_affinity_matches(self):
        from karpenter_tpu.models.pod import PodAffinityTerm

        pods = []
        for i in range(4):
            p = make_pod(f"ha-{i}", cpu=0.25)
            p.metadata.labels = {"app": "db"}
            p.spec.pod_anti_affinity = [
                PodAffinityTerm(topology_key=l.LABEL_HOSTNAME, label_selector={"app": "db"})
            ]
            pods.append(p)
        host, tpu = self._both(pods, n_types=64)
        assert_same_packing(host, tpu)
        assert len(tpu.claims) == 4

    def test_affinity_matches(self):
        from karpenter_tpu.models.pod import PodAffinityTerm

        pods = []
        for i in range(4):
            p = make_pod(f"af-{i}", cpu=0.5)
            p.metadata.labels = {"app": "cache"}
            p.spec.pod_affinity = [
                PodAffinityTerm(topology_key=l.LABEL_TOPOLOGY_ZONE, label_selector={"app": "cache"})
            ]
            pods.append(p)
        host, tpu = self._both(pods)
        assert_same_packing(host, tpu)
        zones = set()
        for c in tpu.claims:
            zones.update(c.requirements.get(l.LABEL_TOPOLOGY_ZONE).values)
        assert len(zones) == 1

    def test_mixed_benchmark_style(self):
        """The reference benchmark's pod mix: generic + zonal TSC +
        hostname TSC + affinity + anti-affinity (1/5 each)."""
        from karpenter_tpu.models.pod import PodAffinityTerm, TopologySpreadConstraint

        rng = np.random.default_rng(11)
        pods = []
        for i in range(40):
            p = make_pod(
                f"mix-{i}",
                cpu=float(rng.choice([0.25, 0.5, 1.0])),
                memory=f"{rng.choice([0.5, 1.0])}Gi",
            )
            kind = i % 5
            if kind == 1:
                p.metadata.labels = {"spread": "zonal"}
                p.spec.topology_spread_constraints = [
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=l.LABEL_TOPOLOGY_ZONE,
                        label_selector={"spread": "zonal"},
                    )
                ]
            elif kind == 2:
                p.metadata.labels = {"spread": "host"}
                p.spec.topology_spread_constraints = [
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=l.LABEL_HOSTNAME,
                        label_selector={"spread": "host"},
                    )
                ]
            elif kind == 3:
                p.metadata.labels = {"aff": "group"}
                p.spec.pod_affinity = [
                    PodAffinityTerm(
                        topology_key=l.LABEL_TOPOLOGY_ZONE, label_selector={"aff": "group"}
                    )
                ]
            elif kind == 4:
                p.metadata.labels = {"anti": "self"}
                p.spec.pod_anti_affinity = [
                    PodAffinityTerm(
                        topology_key=l.LABEL_HOSTNAME, label_selector={"anti": "self"}
                    )
                ]
            pods.append(p)
        host, tpu = self._both(pods, n_types=48)
        assert_same_packing(host, tpu)


class TestIncrementalCompat:
    """The tier-2 fast path classifies (claim, key) rows by comb==pod /
    comb==claim; these cases force the remaining classes."""

    def _solve_both(self, pods, n_types=32):
        from karpenter_tpu.controllers.provisioning.topology import (
            Topology,
            build_universe_domains,
        )

        templates = build_templates([(default_pool(), instance_types(n_types))])
        universe = build_universe_domains(templates)
        host = HostScheduler(
            templates, topology=Topology.build(pods, universe)
        ).solve(pods)
        tpu = TPUScheduler(templates).solve(
            pods, topology=Topology.build(pods, universe)
        )
        assert_same_packing(host, tpu)
        return host, tpu

    def test_partial_overlap_selectors_force_exact_fallback(self):
        """Zone selectors {1,2} and {2,3} interleaved: the second pod's
        comb on the zone key ({2}) equals neither its own row nor the
        claim's — the lax.cond fallback must reproduce full semantics."""
        from karpenter_tpu.models.pod import NodeAffinity, NodeSelectorTerm

        pods = []
        for i in range(8):
            zones = (
                ["test-zone-1", "test-zone-2"]
                if i % 2 == 0
                else ["test-zone-2", "test-zone-3"]
            )
            p = make_pod(f"p-{i}", cpu=0.5, memory="1Gi")
            p.spec.node_affinity = NodeAffinity(
                required=[
                    NodeSelectorTerm(
                        match_expressions=[
                            {
                                "key": l.LABEL_TOPOLOGY_ZONE,
                                "operator": "In",
                                "values": zones,
                            }
                        ]
                    )
                ]
            )
            pods.append(p)
        host, tpu = self._solve_both(pods)
        assert not tpu.unschedulable

    def test_disjoint_selectors_never_share_a_claim(self):
        """Disjoint zone selectors make comb empty on the zone key — the
        claims must stay separate in both engines."""
        pods = []
        for i in range(6):
            zone = "test-zone-1" if i % 2 == 0 else "test-zone-2"
            pods.append(
                make_pod(
                    f"p-{i}",
                    cpu=0.5,
                    memory="1Gi",
                    node_selector={l.LABEL_TOPOLOGY_ZONE: zone},
                )
            )
        host, tpu = self._solve_both(pods)
        for c in tpu.claims:
            assert len(c.requirements.get(l.LABEL_TOPOLOGY_ZONE).values) == 1

    def test_namespace_scoped_kinds_not_deduped(self):
        """Content-identical pods in different namespaces belong to
        different (per-namespace) topology groups — kind dedup must keep
        them apart or anti-affinity leaks across namespaces."""
        pods = []
        for i in range(4):
            p = make_pod(f"p-{i}", cpu=0.5, memory="1Gi")
            p.metadata.namespace = "ns-a" if i % 2 == 0 else "ns-b"
            p.metadata.labels = {"app": "nginx"}
            from karpenter_tpu.models.pod import PodAffinityTerm

            p.spec.pod_anti_affinity = [
                PodAffinityTerm(
                    topology_key=l.LABEL_HOSTNAME, label_selector={"app": "nginx"}
                )
            ]
            pods.append(p)
        host, tpu = self._solve_both(pods)
        # anti-affinity is namespace-scoped: same-namespace pods separate,
        # cross-namespace pods may share -> 2 nodes of one pod per namespace
        assert len(tpu.claims) == 2
        for c in tpu.claims:
            assert len({p.metadata.namespace for p in c.pods}) == len(c.pods)

    def test_narrowing_selector_lands_on_wider_claim(self):
        """A wide-selector pod opens a claim; a narrower pod (comb == pod
        row, the precomputed-table class) joins and narrows it."""
        from karpenter_tpu.models.pod import NodeAffinity, NodeSelectorTerm

        wide = make_pod("wide", cpu=0.5, memory="1Gi")
        wide.spec.node_affinity = NodeAffinity(
            required=[
                NodeSelectorTerm(
                    match_expressions=[
                        {
                            "key": l.LABEL_TOPOLOGY_ZONE,
                            "operator": "In",
                            "values": ["test-zone-1", "test-zone-2", "test-zone-3"],
                        }
                    ]
                )
            ]
        )
        narrow = make_pod(
            "narrow",
            cpu=0.5,
            memory="1Gi",
            node_selector={l.LABEL_TOPOLOGY_ZONE: "test-zone-2"},
        )
        host, tpu = self._solve_both([wide, narrow])
        assert not tpu.unschedulable


class TestMinValues:
    def _pool(self, key, mv):
        return default_pool(
            "mv",
            requirements=[{"key": key, "operator": "Exists", "minValues": mv}],
        )

    def test_min_values_name_key_limits_claims(self):
        """instance-type minValues=3: a claim must keep >=3 viable types, so
        it stops accepting pods earlier than an unconstrained claim."""
        pool = self._pool(l.LABEL_INSTANCE_TYPE, 3)
        pods = [make_pod(f"p-{i}", cpu=1.0, memory="1Gi") for i in range(12)]
        templates = build_templates([(pool, instance_types(64))])
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        assert not tpu.unschedulable
        for c in tpu.claims:
            assert len({it.name for it in c.instance_types}) >= 3

    def test_min_values_family_key(self):
        pool = self._pool("karpenter-tpu.sh/instance-family", 2)
        pods = [make_pod(f"p-{i}", cpu=0.5) for i in range(6)]
        templates = build_templates([(pool, instance_types(64))])
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        for c in tpu.claims:
            families = set()
            for it in c.instance_types:
                families.update(it.requirements.get("karpenter-tpu.sh/instance-family").values)
            assert len(families) >= 2

    def test_min_values_on_undefined_key(self):
        """Types that don't define the min-keyed label contribute ZERO
        values (Values() parity) — the floor must fail, not pass through
        the identity encoding."""
        pool = self._pool("example.com/undefined-everywhere", 2)
        pods = [make_pod("p", cpu=0.5)]
        templates = build_templates([(pool, instance_types(16))])
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        assert len(tpu.unschedulable) == 1

    def test_min_values_complement_catalog_parity(self):
        """Instance types carrying NotIn requirements on the counted key
        contribute their RAW value set — Go's Requirement.Values()
        (requirement.go:282-284) returns the stored set regardless of
        operator, and both engines must count identically."""
        from karpenter_tpu.scheduling import Operator, Requirement

        pool = self._pool("example.com/tier", 3)
        its = instance_types(16)
        for i, it in enumerate(its):
            it.requirements.add(
                Requirement.new("example.com/tier", Operator.NOT_IN, f"tier-{i % 4}")
            )
        pods = [make_pod(f"p-{i}", cpu=0.5) for i in range(4)]
        templates = build_templates([(pool, its)])
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        # 4 distinct excluded values across the catalog >= floor of 3
        assert not host.unschedulable

    def test_unsatisfiable_min_values(self):
        """minValues beyond the catalog's diversity -> unschedulable."""
        pool = self._pool("karpenter-tpu.sh/instance-family", 99)
        pods = [make_pod("p", cpu=0.5)]
        templates = build_templates([(pool, instance_types(16))])
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        assert len(tpu.unschedulable) == 1

    def test_min_values_best_effort_relaxes(self):
        """The same unsatisfiable floor under MinValuesPolicy=BestEffort:
        the pod schedules, the claim is flagged relaxed, and the floor is
        lowered to the achievable distinct-value count
        (nodeclaim.go:606-613 + scheduler.go:763-772)."""
        pool = self._pool("karpenter-tpu.sh/instance-family", 99)
        pods = [make_pod("p", cpu=0.5)]
        templates = build_templates([(pool, instance_types(16))])
        host = HostScheduler(templates, min_values_policy="BestEffort").solve(pods)
        tpu = TPUScheduler(templates, min_values_policy="BestEffort").solve(pods)
        assert_same_packing(host, tpu)
        for r in (host, tpu):
            assert not r.unschedulable
            [claim] = r.claims
            assert claim.min_values_relaxed
            # instance_types(16) spans exactly 4 families
            assert (
                claim.requirements.get("karpenter-tpu.sh/instance-family").min_values == 4
            )


class TestHostPortsAndVolumes:
    def test_hostport_conflict_separates_pods(self):
        from karpenter_tpu.models.pod import HostPort

        pods = []
        for i in range(3):
            p = make_pod(f"hp-{i}", cpu=0.25)
            p.spec.host_ports = [HostPort(port=8080)]
            pods.append(p)
        templates = build_templates([(default_pool(), instance_types(64))])
        host = HostScheduler(templates).solve(pods)
        tpu = TPUScheduler(templates).solve(pods)
        assert_same_packing(host, tpu)
        assert not tpu.unschedulable
        # one 8080 per node
        assert len(tpu.claims) == 3

    def test_wildcard_ip_conflicts_with_specific(self):
        from karpenter_tpu.models.pod import HostPort

        a = make_pod("a", cpu=0.25)
        a.spec.host_ports = [HostPort(port=53, host_ip="0.0.0.0")]
        b = make_pod("b", cpu=0.25)
        b.spec.host_ports = [HostPort(port=53, host_ip="10.0.0.1")]
        c = make_pod("c", cpu=0.25)
        c.spec.host_ports = [HostPort(port=53, protocol="UDP")]  # different proto: no conflict
        templates = build_templates([(default_pool(), instance_types(64))])
        host = HostScheduler(templates).solve([a, b, c])
        tpu = TPUScheduler(templates).solve([a, b, c])
        assert_same_packing(host, tpu)
        assert len(tpu.claims) == 2  # a and b separated; c shares with one

    def test_hostport_vs_existing_node(self):
        from karpenter_tpu.models.pod import HostPort

        templates = build_templates([(default_pool(), instance_types(16))])
        pod = make_pod("p", cpu=0.25)
        pod.spec.host_ports = [HostPort(port=443)]

        def factory():
            n = make_existing("node-a", 0)
            n.host_ports = [("0.0.0.0", 443, "TCP")]
            return [n]

        host = HostScheduler(templates, existing_nodes=factory()).solve([pod])
        tpu = TPUScheduler(templates).solve([pod], factory())
        assert_same_packing(host, tpu)
        assert not host.existing_assignments  # port taken on the node
        assert host.node_count == 1

    def test_volume_zone_requirement(self):
        from karpenter_tpu.scheduling.hostports import PersistentVolumeClaim, StorageClass
        from karpenter_tpu.scheduling.volumes import volume_requirement_alternatives

        pod = make_pod("p", cpu=0.25)
        pod.spec.pvc_names = ["data"]
        pvc = PersistentVolumeClaim(storage_class="zonal")
        pvc.metadata.name = "data"
        sc = StorageClass(zones=["test-zone-2"])
        sc.metadata.name = "zonal"
        alts = volume_requirement_alternatives(pod, {"data": pvc}, {"zonal": sc})
        assert len(alts) == 1
        assert sorted(alts[0].get(l.LABEL_TOPOLOGY_ZONE).values) == ["test-zone-2"]

        templates = build_templates([(default_pool(), instance_types(16))])
        vol = {pod.uid: alts}
        host = HostScheduler(templates, volume_reqs=vol).solve([pod])
        tpu = TPUScheduler(templates).solve([pod], volume_reqs=vol)
        assert_same_packing(host, tpu)
        for c in tpu.claims:
            assert sorted(c.requirements.get(l.LABEL_TOPOLOGY_ZONE).values) == ["test-zone-2"]

    def test_bound_pvc_pins_zone(self):
        from karpenter_tpu.scheduling.hostports import PersistentVolumeClaim
        from karpenter_tpu.scheduling.volumes import volume_requirement_alternatives

        pod = make_pod("p")
        pod.spec.pvc_names = ["data"]
        pvc = PersistentVolumeClaim(bound_zone="test-zone-3")
        pvc.metadata.name = "data"
        alts = volume_requirement_alternatives(pod, {"data": pvc}, {})
        assert len(alts) == 1
        assert sorted(alts[0].get(l.LABEL_TOPOLOGY_ZONE).values) == ["test-zone-3"]


class TestPackingQuality:
    def test_bin_utilization(self):
        """Packing must fill nodes densely. instance_types(64) spans cpu
        sizes 1..64 (8 shapes per size), so 64 x 1cpu pods fit in a couple
        of large nodes rather than one node per pod."""
        pods = [make_pod(f"p-{i}", cpu=1.0, memory="1Gi") for i in range(64)]
        templates = build_templates([(default_pool(), instance_types(64))])
        result = TPUScheduler(templates).solve(pods)
        assert result.node_count <= 2
        assert not result.unschedulable

    def test_dense_on_small_catalog(self):
        """With only 1/2/4-cpu shapes (instance_types(24)), 64 cores of pods
        need ~64/3.8 nodes — dense given the catalog, not one per pod."""
        pods = [make_pod(f"p-{i}", cpu=1.0, memory="1Gi") for i in range(64)]
        templates = build_templates([(default_pool(), instance_types(24))])
        result = TPUScheduler(templates).solve(pods)
        assert result.node_count <= 24
        assert not result.unschedulable
