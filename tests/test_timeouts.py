"""Timeout semantics, fake-clock driven.

Reference degraded behaviors reproduced here:
- provisioner.go:415 — the 1m Solve deadline fails the REMAINING queue,
  the placed prefix stands, and no further relaxation rounds run.
- multinodeconsolidation.go:35,142-153 — the 1m prefix search returns the
  last VALID command instead of discarding the pass's work.
- singlenodeconsolidation.go:33 — the 3m candidate walk stops; unreached
  candidates wait for the next poll.
"""

from dataclasses import dataclass, field

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.controllers.disruption.methods import (
    MULTI_NODE_CONSOLIDATION_TIMEOUT_SECONDS,
    SINGLE_NODE_CONSOLIDATION_TIMEOUT_SECONDS,
    MultiNodeConsolidation,
    SingleNodeConsolidation,
)
from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
from karpenter_tpu.controllers.provisioning.host_scheduler import (
    SOLVE_TIMEOUT_REASON,
    HostScheduler,
    SchedulingResult,
)
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import NodeAffinity, PreferredSchedulingTerm, make_pod
from karpenter_tpu.utils.clock import FakeClock


def default_pool() -> NodePool:
    pool = NodePool()
    pool.metadata.name = "default"
    return pool


# -- fake candidates (just the attribute surface methods.py touches) ---------


@dataclass
class _FakeStatus:
    last_pod_event_time: float | None = None


@dataclass
class _FakeMeta:
    creation_timestamp: float = 0.0
    labels: dict = field(default_factory=lambda: {
        l.CAPACITY_TYPE_LABEL_KEY: l.CAPACITY_TYPE_ON_DEMAND
    })


@dataclass
class _FakeClaim:
    status: _FakeStatus = field(default_factory=_FakeStatus)
    metadata: _FakeMeta = field(default_factory=_FakeMeta)


@dataclass
class _FakeStateNode:
    node_claim: _FakeClaim = field(default_factory=_FakeClaim)
    node: object = None


def _consolidatable_pool() -> NodePool:
    pool = default_pool()
    pool.spec.disruption.consolidation_policy = "WhenEmptyOrUnderutilized"
    pool.spec.disruption.consolidate_after_seconds = 0.0
    return pool


@dataclass
class _FakeCandidate:
    name: str
    savings_ratio: float
    price: float = 1.0
    owned_by_static: bool = False
    nodepool: NodePool = field(default_factory=_consolidatable_pool)
    state_node: _FakeStateNode = field(default_factory=_FakeStateNode)
    reschedulable_pods: list = field(default_factory=list)
    instance_type: object = None
    # ordinary node (not a slice host): methods group candidates into
    # atomic units by this key
    gang_key: object = None
    disruption_cost: float = 1.0


def _ok_result():
    """A delete-consolidation verdict: everything fits without new claims."""
    return SchedulingResult(claims=[], unschedulable=[], assignments={})


class TestMultiNodeTimeout:
    def test_returns_last_valid_command_on_deadline(self):
        clock = FakeClock(start=0.0)
        calls = []

        def simulate(candidates, deadline=None):
            calls.append(len(candidates))
            # each what-if burns 40s of the 60s budget
            clock.step(40.0)
            return _ok_result(), set()

        method = MultiNodeConsolidation(simulate, clock)
        cands = [_FakeCandidate(f"n-{i}", savings_ratio=i) for i in range(4)]
        cmd = method.compute(cands, budgets={"default": 100})
        # binary search: mid=2 valid (t=40), mid=3 valid (t=80 > 60s
        # deadline) -> next iteration times out and returns the LAST VALID
        # prefix rather than an empty command
        assert not cmd.is_empty
        assert len(cmd.candidates) == 3
        assert calls == [2, 3]
        assert clock.now() < MULTI_NODE_CONSOLIDATION_TIMEOUT_SECONDS * 2

    def test_full_search_without_deadline_pressure(self):
        clock = FakeClock(start=0.0)

        def simulate(candidates, deadline=None):
            clock.step(1.0)  # fast what-ifs: the search completes
            return _ok_result(), set()

        method = MultiNodeConsolidation(simulate, clock)
        cands = [_FakeCandidate(f"n-{i}", savings_ratio=i) for i in range(4)]
        cmd = method.compute(cands, budgets={"default": 100})
        assert len(cmd.candidates) == 4  # the whole batch consolidates

    def test_simulate_receives_method_deadline(self):
        clock = FakeClock(start=100.0)
        seen = []

        def simulate(candidates, deadline=None):
            seen.append(deadline)
            return _ok_result(), set()

        method = MultiNodeConsolidation(simulate, clock)
        cands = [_FakeCandidate(f"n-{i}", savings_ratio=i) for i in range(2)]
        method.compute(cands, budgets={"default": 100})
        assert seen and all(
            d == 100.0 + MULTI_NODE_CONSOLIDATION_TIMEOUT_SECONDS for d in seen
        )


class TestSingleNodeTimeout:
    def test_walk_stops_at_deadline(self):
        clock = FakeClock(start=0.0)
        calls = []

        def simulate(candidates, deadline=None):
            calls.append(candidates[0].name)
            clock.step(200.0)  # each candidate overruns the 3m budget
            # two replacement claims -> not a valid single-node command
            from karpenter_tpu.controllers.provisioning.host_scheduler import SimClaim

            claims = [
                SimClaim(template=None, requirements=None, used={}, instance_types=[],
                         pods=[], slot=i)
                for i in range(2)
            ]
            return SchedulingResult(claims=claims, unschedulable=[], assignments={}), set()

        method = SingleNodeConsolidation(simulate, clock)
        cands = [_FakeCandidate(f"n-{i}", savings_ratio=i) for i in range(5)]
        cmd = method.compute(cands, budgets={"default": 100})
        assert cmd.is_empty
        # only the first candidate was evaluated; the rest wait for the
        # next 10s poll instead of stalling the controller for 16m
        assert calls == ["n-0"]
        assert clock.now() >= SINGLE_NODE_CONSOLIDATION_TIMEOUT_SECONDS


class TestSolveTimeout:
    def test_host_deadline_fails_remaining_queue(self):
        templates = build_templates([(default_pool(), instance_types(16))])
        t = {"v": 0.0}

        def now() -> float:
            t["v"] += 50.0
            return t["v"]

        host = HostScheduler(templates, deadline=120.0, now=now)
        pods = [make_pod(f"p-{i}", cpu=0.5) for i in range(3)]
        result = host.solve(pods)
        # pods 1+2 placed (t=50,100 < 120); pod 3 hit the expired deadline
        placed = sum(len(c.pods) for c in result.claims)
        assert placed == 2
        assert [r for _, r in result.unschedulable] == [SOLVE_TIMEOUT_REASON]

    def test_tpu_deadline_stops_relaxation(self):
        templates = build_templates([(default_pool(), instance_types(16))])
        pod = make_pod("p", cpu=0.5)
        pod.spec.node_affinity = NodeAffinity(
            preferred=[
                PreferredSchedulingTerm(
                    10,
                    [{"key": l.LABEL_TOPOLOGY_ZONE, "operator": "In",
                      "values": ["zone-nowhere"]}],
                )
            ]
        )
        clock = FakeClock(start=0.0)
        sched = TPUScheduler(templates)
        # expired before round 2: the pod would be rescued by shedding the
        # preference, but the deadline stops the ladder after round 1
        result = sched.solve([pod], deadline=clock.now() - 1.0, now=clock.now)
        assert len(result.unschedulable) == 1
        # same problem with headroom relaxes and schedules
        result2 = sched.solve([pod], deadline=clock.now() + 3600.0, now=clock.now)
        assert not result2.unschedulable
