"""Disruption what-if benchmark: batched vs sequential candidate evaluation.

The tensorized twin of the reference's per-candidate SimulateScheduling
loop (multinodeconsolidation.go:136-183, singlenodeconsolidation.go:33-146):
N single-candidate scenarios evaluated as ONE vmapped device dispatch
(TPUScheduler.whatif_batch) against N sequential full re-solves
(Provisioner.simulate). Differential parity between the two paths is pinned
by tests/test_whatif.py; this measures the wall-clock win.

Prints ONE JSON line:
  {"metric": "whatif_batch_speedup", "value": <x faster>, "unit": "x",
   "vs_baseline": <same>, "detail": {...}}
"""

from __future__ import annotations

import json
import time

N_CANDIDATES = 100
SEQUENTIAL_SAMPLE = 10  # full sequential sweep extrapolated from a sample


def build_cluster(n_nodes: int):
    """The shared fixture cluster (karpenter_tpu.testing) the parity tests
    also pin — the benchmark measures the exact same bootstrap."""
    from karpenter_tpu.testing import build_bound_cluster

    _clock, store, _cloud, mgr = build_bound_cluster(n_pods=n_nodes, pod_cpu=2.0)
    return store, mgr


def main() -> None:
    from karpenter_tpu.envelope.sampler import measured
    from karpenter_tpu.testing import FakeCandidate
    from karpenter_tpu.utils import accel

    platform = "tpu" if accel.accelerator_usable() else "cpu"
    if platform == "cpu":
        accel.force_cpu()

    # host resource envelope around the whole bench: host_rss_mb/cpu_s land
    # in the detail like every bench.py stage (envelope/sampler.py)
    envelope = {}
    with measured(envelope, stage="whatif_bench"):
        store, mgr = build_cluster(N_CANDIDATES)
        by_node: dict[str, list] = {}
        for p in store.pods():
            if p.spec.node_name:
                by_node.setdefault(p.spec.node_name, []).append(p)
        candidates = [
            FakeCandidate(name, pods) for name, pods in sorted(by_node.items())
        ]
        scenarios = [[c] for c in candidates]
        prov = mgr.provisioner

        # warm both paths (compile cache) before timing
        warm = prov.simulate_batch(scenarios)
        assert warm is not None, "batch path gated"
        prov.simulate({candidates[0].name}, candidates[0].reschedulable_pods)

        t0 = time.perf_counter()
        signals = prov.simulate_batch(scenarios)
        t_batch = time.perf_counter() - t0
        assert signals is not None and len(signals) == len(scenarios)

        t0 = time.perf_counter()
        for c in candidates[:SEQUENTIAL_SAMPLE]:
            prov.simulate({c.name}, c.reschedulable_pods)
        t_seq_sample = time.perf_counter() - t0
        t_seq = t_seq_sample * (len(candidates) / SEQUENTIAL_SAMPLE)

    from bench import WHATIF_MIN_SPEEDUP_X

    speedup = t_seq / t_batch if t_batch > 0 else float("inf")
    print(
        json.dumps(
            {
                "metric": "whatif_batch_speedup",
                "value": round(speedup, 2),
                "unit": "x",
                "vs_baseline": round(speedup, 2),
                "detail": {
                    "candidates": len(candidates),
                    "batch_s": round(t_batch, 3),
                    "sequential_s_extrapolated": round(t_seq, 3),
                    "sequential_sample": SEQUENTIAL_SAMPLE,
                    "platform": platform,
                    "feasible": sum(1 for ok, _ in signals if ok),
                    "gate_min_speedup_x": WHATIF_MIN_SPEEDUP_X,
                    "gate_ok": speedup >= WHATIF_MIN_SPEEDUP_X,
                    **envelope,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
