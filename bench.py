"""Headline benchmark: scheduling throughput.

Mirrors the reference's in-process scheduler benchmark
(scheduling_benchmark_test.go): diverse pods against a fake catalog with
the reference's 1/5 mix — generic, TSC-zone, TSC-hostname, pod-affinity,
pod-anti-affinity (makeDiversePods, :259-272) — through the full pipeline:
host encode, device scan-FFD solve, host decode to claims.

Stages (sizes scale down on CPU fallback so the bench stays bounded):
  1. selectors-only 2048 x 400   — round-1-comparable number
  2. reference mix (headline)    — 16384 x 400 on TPU / 4096 x 400 on CPU
  3. north-star scale probe      — 100k x 1k selector mix (TPU only;
                                    BASELINE.json config #5 workload)

Prints ONE final JSON line:
  {"metric": ..., "value": N, "unit": "pods/sec", "vs_baseline": N/100,
   "detail": {per-stage wall/encode/device/decode splits, platform}}
"""

from __future__ import annotations

import json
import os
import time

BASELINE_PODS_PER_SEC = 100.0  # reference MinPodsPerSec gate (:58)


def selector_pods(n):
    import numpy as np

    from karpenter_tpu.models import labels as l
    from karpenter_tpu.models.pod import make_pod

    rng = np.random.default_rng(0)
    zones = ("test-zone-1", "test-zone-2", "test-zone-3", "test-zone-4")
    pods = []
    for i in range(n):
        sel = {}
        if i % 5 == 1:
            sel[l.LABEL_TOPOLOGY_ZONE] = zones[i % len(zones)]
        if i % 5 == 2:
            sel[l.LABEL_ARCH] = l.ARCH_AMD64
        if i % 5 == 3:
            sel[l.CAPACITY_TYPE_LABEL_KEY] = l.CAPACITY_TYPE_ON_DEMAND
        pods.append(
            make_pod(
                f"p-{i}",
                cpu=float(rng.choice([0.1, 0.25, 0.5, 1.0, 2.0, 4.0])),
                memory=f"{rng.choice([0.25, 0.5, 1.0, 2.0, 4.0])}Gi",
                node_selector=sel,
            )
        )
    return pods


def zonal_pods(n, kinds=4, prefix="zb"):
    """Kscan-shaped pods for the shard bench stage: each kind carries a
    zone-spread constraint with a DISJOINT selector and a saturating size,
    so the kscan dp-speculative path (ISSUE 13) engages and commits."""
    from karpenter_tpu.models import labels as l
    from karpenter_tpu.models.pod import TopologySpreadConstraint, make_pod

    pods = []
    per = max(n // kinds, 1)
    for i in range(n):
        k = min(i // per, kinds - 1)
        p = make_pod(f"{prefix}-{i}", cpu=2.0, memory="1Gi")
        p.metadata.labels = {"grp": str(k), "spread": f"z{k}"}
        p.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=l.LABEL_TOPOLOGY_ZONE,
                label_selector={"spread": f"z{k}"},
            )
        ]
        pods.append(p)
    return pods


def hostname_pods(n, kinds=4, prefix="hb"):
    """Topology-BEARING fill pods for the shard bench stage (ISSUE 14):
    hostname-spread kinds with DISJOINT selectors keep the fill route but
    carry hg state, so the topo_fill speculation family engages; the
    saturating size lets groups commit."""
    from karpenter_tpu.models import labels as l
    from karpenter_tpu.models.pod import TopologySpreadConstraint, make_pod

    pods = []
    per = max(n // kinds, 1)
    for i in range(n):
        k = min(i // per, kinds - 1)
        p = make_pod(f"{prefix}-{i}", cpu=2.0, memory="1Gi")
        p.metadata.labels = {"grp": str(k), "hspread": f"h{k}"}
        p.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=l.LABEL_HOSTNAME,
                label_selector={"hspread": f"h{k}"},
            )
        ]
        pods.append(p)
    return pods


def perpod_pods(n, kinds=4, prefix="pb"):
    """Per-pod-routed pods for the shard bench stage (ISSUE 14): TWO
    distinct vg keys per kind (zone + capacity-type spread) defeat the
    single-key kscan check, so the run takes the per-pod scan and the
    solve_perpod_dp speculation family engages."""
    from karpenter_tpu.models import labels as l
    from karpenter_tpu.models.pod import TopologySpreadConstraint, make_pod

    pods = []
    per = max(n // kinds, 1)
    for i in range(n):
        k = min(i // per, kinds - 1)
        p = make_pod(f"{prefix}-{i}", cpu=2.0, memory="1Gi")
        p.metadata.labels = {"grp": str(k), "spread": f"p{k}"}
        p.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=l.LABEL_TOPOLOGY_ZONE,
                label_selector={"spread": f"p{k}"},
            ),
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=l.CAPACITY_TYPE_LABEL_KEY,
                label_selector={"spread": f"p{k}"},
            ),
        ]
        pods.append(p)
    return pods


def existing_sim_nodes(n=2, cpu_avail=4.0):
    """Part-full existing nodes for the shard bench stage (ISSUE 14): the
    dp rows racing to debit them exercise the disjoint-touch verdict bit
    of the `existing` speculation family."""
    from karpenter_tpu.controllers.provisioning.host_scheduler import (
        ExistingSimNode,
    )
    from karpenter_tpu.models import labels as l
    from karpenter_tpu.scheduling import Requirements
    from karpenter_tpu.utils import resources as res

    nodes = []
    for i in range(n):
        name = f"exist-{i}"
        labels = {
            l.LABEL_TOPOLOGY_ZONE: "test-zone-1",
            l.LABEL_INSTANCE_TYPE: "s-4x-amd64",
            l.CAPACITY_TYPE_LABEL_KEY: l.CAPACITY_TYPE_ON_DEMAND,
            l.LABEL_ARCH: l.ARCH_AMD64,
            l.LABEL_OS: "linux",
            l.LABEL_HOSTNAME: name,
            l.NODEPOOL_LABEL_KEY: "default",
        }
        nodes.append(
            ExistingSimNode(
                name=name,
                index=i,
                requirements=Requirements.from_labels(labels),
                available={
                    res.CPU: cpu_avail,
                    res.MEMORY: float(8 * 2**30),
                    res.PODS: 50.0,
                },
            )
        )
    return nodes


def mixed_pods(n):
    """The reference benchmark's makeDiversePods: equal fifths of generic,
    TSC-zone, TSC-hostname, zone pod-affinity, hostname pod-anti-affinity
    (all anti pods share one label, scheduling_benchmark_test.go:274-300)."""
    import numpy as np

    from karpenter_tpu.models import labels as l
    from karpenter_tpu.models.pod import (
        PodAffinityTerm,
        TopologySpreadConstraint,
        make_pod,
    )

    rng = np.random.default_rng(0)
    pods = []
    for i in range(n):
        p = make_pod(
            f"p-{i}",
            cpu=float(rng.choice([0.1, 0.25, 0.5, 1.0, 2.0])),
            memory=f"{rng.choice([0.25, 0.5, 1.0, 2.0])}Gi",
        )
        kind = i % 5
        if kind == 1:
            p.metadata.labels = {"spread": "zonal"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=l.LABEL_TOPOLOGY_ZONE,
                    label_selector={"spread": "zonal"},
                )
            ]
        elif kind == 2:
            p.metadata.labels = {"spread": "host"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=l.LABEL_HOSTNAME,
                    label_selector={"spread": "host"},
                )
            ]
        elif kind == 3:
            p.metadata.labels = {"aff": "group"}
            p.spec.pod_affinity = [
                PodAffinityTerm(
                    topology_key=l.LABEL_TOPOLOGY_ZONE, label_selector={"aff": "group"}
                )
            ]
        elif kind == 4:
            p.metadata.labels = {"app": "nginx"}
            p.spec.pod_anti_affinity = [
                PodAffinityTerm(
                    topology_key=l.LABEL_HOSTNAME, label_selector={"app": "nginx"}
                )
            ]
        pods.append(p)
    return pods


def make_templates(n_types):
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.controllers.provisioning import build_templates
    from karpenter_tpu.models.nodepool import NodePool

    pool = NodePool()
    pool.metadata.name = "default"
    return build_templates([(pool, instance_types(n_types))])


def mv_templates(n_types, mv=2):
    """Templates whose pool carries an instance-type minValues floor —
    the enforced-minValues class rung 1 (ISSUE 20) admits to perpod-dp."""
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.controllers.provisioning import build_templates
    from karpenter_tpu.models import labels as l
    from karpenter_tpu.models.nodepool import NodePool

    pool = NodePool()
    pool.metadata.name = "default"
    pool.spec.template.spec.requirements = [
        {"key": l.LABEL_INSTANCE_TYPE, "operator": "Exists", "minValues": mv}
    ]
    return build_templates([(pool, instance_types(n_types))])


def host_solve(templates, pods, budgets=None):
    """The Go-FFD oracle on the identical problem: same templates, same
    internally-built topology the device path uses when none is injected
    (scheduler.py _encode: Topology.build over the universe domains)."""
    from karpenter_tpu.controllers.provisioning.host_scheduler import HostScheduler
    from karpenter_tpu.controllers.provisioning.topology import (
        Topology,
        build_universe_domains,
    )

    topo = Topology.build(pods, build_universe_domains(templates, []), [])
    t0 = time.perf_counter()
    result = HostScheduler(templates, budgets=budgets, topology=topo).solve(
        list(pods)
    )
    return result, time.perf_counter() - t0


def _wf_digest(timings):
    """Compact per-round waterfall digest for bench JSONs (ISSUE 15):
    the round wall, the reconciled unattributed remainder, and the
    per-segment self-times. The ordered span list stays on the ledger
    record — the bench file only carries the rollup bench_diff compares."""
    wf = (timings or {}).get("waterfall")
    if not isinstance(wf, dict):
        return None
    return {
        "wall_s": wf.get("wall_s"),
        "other_frac": wf.get("other_frac"),
        "segments": wf.get("segments"),
    }


def run_stage(pods, n_types, max_claims, warm_runs=2, host_parity=False, mesh=None):
    from karpenter_tpu.controllers.provisioning import TPUScheduler
    from karpenter_tpu.envelope.sampler import measured
    from karpenter_tpu.obs import ledger as obs_ledger

    ledger_seq0 = obs_ledger.LEDGER.seq()
    # host resource envelope over the whole stage (cold solve included):
    # fills host_rss_mb (P95 of the RSS series) + cpu_s + avg_cores
    envelope = {}
    with measured(envelope, stage=f"stage_{len(pods)}x{n_types}"):
        templates = make_templates(n_types)
        sched = TPUScheduler(
            templates, pod_pad=len(pods), max_claims=max_claims, mesh=mesh
        )
        t0 = time.perf_counter()
        result = sched.solve(pods)  # cold: compile + run
        cold_s = time.perf_counter() - t0
        assert not result.unschedulable, f"{len(result.unschedulable)} unschedulable"
        best, timings = None, dict(sched.last_timings)
        for _ in range(warm_runs):
            t0 = time.perf_counter()
            result = sched.solve(pods)
            wall = time.perf_counter() - t0
            if best is None or wall < best:
                best, timings = wall, dict(sched.last_timings)
        best = best if best is not None else cold_s
    out = {
        "pods": len(pods),
        "types": n_types,
        "pods_per_sec": round(len(pods) / best, 1),
        "wall_s": round(best, 4),
        "cold_s": round(cold_s, 2),  # includes XLA compile
        "encode_s": round(timings["encode_s"], 4),
        "device_s": round(timings["device_s"], 4),
        "decode_s": round(timings["decode_s"], 4),
        "nodes": result.node_count,
        "total_price_per_hour": round(result.total_price(), 2),
        **envelope,
    }
    if "pipeline" in timings:
        # pipelined solve: the headline overlap number plus the per
        # chunk-group split — each chunk carries its own host_rss_mb /
        # cpu_s envelope sample (satellite: chunk-group nesting, not just
        # the per-solve stage envelope above)
        pl = timings["pipeline"]
        out["overlap_frac"] = pl["overlap_frac"]
        out["pipeline"] = pl
    if timings.get("scan"):
        # claims-axis occupancy: window size vs live high-water, frozen
        # bank, spills, compactions (bench --report-scan prints these)
        out["scan"] = timings["scan"]
    if timings.get("shard"):
        # per-shard record: mesh extents, dp merge/commit counters,
        # per-group pod counts, replicated-bytes estimate (ISSUE 8;
        # bench --report-shard prints these)
        out["shard"] = timings["shard"]
    if timings.get("padding"):
        out["padding"] = timings["padding"]
    wf = _wf_digest(timings)
    if wf:
        # the best warm round's critical-path waterfall rollup — the
        # segments bench_diff/--baseline compare run-over-run (ISSUE 15)
        out["waterfall"] = wf
    # the stage's flight-recorder digest (bench --report-rounds prints it)
    out["rounds"] = _ledger_rounds_summary(ledger_seq0)
    if host_parity:
        # density on the record: the north star is throughput AT Go-FFD
        # packing density, so the oracle's nodes/price sit next to the
        # device's in every BENCH file (scheduling_benchmark_test.go:211-214)
        href, host_s = host_solve(templates, pods)
        out["host_nodes"] = href.node_count
        out["host_price_per_hour"] = round(href.total_price(), 2)
        out["host_wall_s"] = round(host_s, 2)
        out["density_parity"] = bool(
            href.node_count == result.node_count
            and abs(href.total_price() - result.total_price()) < 1e-6
        )
    return out


# The whatif-batch regression floor (VERDICT r5 weak #4: 22x -> 13.8x slid
# with no gate noticing). tests/test_perf_gate.py asserts the same number
# on TPU hardware; the bench records it so the JSON shows gate status.
WHATIF_MIN_SPEEDUP_X = 10.0

# Resident incremental solver gate (ISSUE 7): p95 per-delta latency of
# resident delta rounds must beat a forced full re-solve of the same
# union by at least this factor at the 16k-resident / 64-pod-delta
# steady state (CPU-measurable; recorded in the bench JSON like the
# whatif gate above).
STEADY_MIN_SPEEDUP_X = 5.0


def run_steady_stage(
    resident_pods=16384,
    delta_pods=64,
    rounds=12,
    seed=0,
    full_sample=4,
    depart_p=0.35,
    max_claims=8192,
):
    """--steady (ISSUE 7): sustained scheduling under a Poisson
    arrival/departure trace against a ResidentSession. A resident base of
    deployment-shaped kinds takes a stream of small delta rounds — each
    round a fresh-kind arrival batch (~Poisson(delta_pods)), sometimes
    preceded by a LIFO departure of the most recent surviving batch (the
    retract path). Reports sustained pods-scheduled/sec, p50/p95/max
    per-delta latency, the resident-hit ratio, and the >= 5x p95 gate vs
    a forced full re-solve of the same union."""
    import numpy as np

    from karpenter_tpu.controllers.provisioning import TPUScheduler
    from karpenter_tpu.envelope.sampler import measured
    from karpenter_tpu.models.pod import make_pod

    def kind_batch(name, n):
        out = []
        for i in range(n):
            p = make_pod(f"{name}-{i}", cpu=1.0, memory="1Gi")
            p.metadata.labels = {"app": name}
            out.append(p)
        return out

    from karpenter_tpu.obs import ledger as obs_ledger

    rng = np.random.default_rng(seed)
    kind_size = 256
    base = []
    for k in range(max(resident_pods // kind_size, 1)):
        base.extend(kind_batch(f"base-{k}", kind_size))
    ledger_seq0 = obs_ledger.LEDGER.seq()
    envelope = {}
    with measured(envelope, stage=f"steady_{resident_pods}x{delta_pods}"):
        templates = make_templates(100)
        session = TPUScheduler(templates, max_claims=max_claims).resident_session()
        t0 = time.perf_counter()
        result = session.solve(list(base))
        cold_s = time.perf_counter() - t0
        assert not result.unschedulable, "steady base did not fully place"
        # steady-state warmup (the measured trace is the service's warm
        # regime, like every other stage's warm_runs): a repeat solve
        # re-sizes the active window to the live high-water — THAT is the
        # resident state a long-running service carries — and one warmup
        # append + retract compiles the delta executables at that window
        session.solve(list(base))
        warm = kind_batch("warmup", delta_pods)
        session.solve(list(base + warm))
        session.solve(list(base))
        live: list[list] = []
        lat: list[float] = []
        modes: list[str] = []
        arrived = departed = 0
        wf_digest = None
        for rnd in range(rounds):
            if live and rng.random() < depart_p:
                departed += len(live[-1])
                live.pop()
            n_new = max(int(rng.poisson(delta_pods)), 1)
            live.append(kind_batch(f"delta-{rnd}", n_new))
            arrived += n_new
            union = base + [p for b in live for p in b]
            t0 = time.perf_counter()
            result = session.solve(list(union))
            lat.append(time.perf_counter() - t0)
            modes.append(session.last_mode)
            # delta rounds don't run the instrumented full path, so keep
            # the waterfall of the trace's most recent full round
            wf_digest = _wf_digest(session.last_timings) or wf_digest
            assert not result.unschedulable
        # forced full re-solve of the same union — today's snapshot path
        # (KTPU_RESIDENT=0 equivalent), warmed so the comparison is
        # steady-state encode/solve/decode, not compile
        full_sched = TPUScheduler(templates, max_claims=max_claims)
        union = base + [p for b in live for p in b]
        full_sched.solve(list(union))  # warm
        full_lat: list[float] = []
        for _ in range(full_sample):
            t0 = time.perf_counter()
            fres = full_sched.solve(list(union))
            full_lat.append(time.perf_counter() - t0)
        assert not fres.unschedulable
    lat_np = np.asarray(lat)
    delta_lat = np.asarray(
        [t for t, m in zip(lat, modes) if m == "delta"] or lat
    )
    p95_delta = float(np.percentile(delta_lat, 95))
    p95_full = float(np.percentile(np.asarray(full_lat), 95))
    speedup = round(p95_full / p95_delta, 1) if p95_delta > 0 else float("inf")
    return {
        "resident_pods": len(base),
        "delta_pods": delta_pods,
        "rounds": rounds,
        "seed": seed,
        "arrived": arrived,
        "departed": departed,
        "cold_s": round(cold_s, 2),
        "p50_delta_s": round(float(np.percentile(delta_lat, 50)), 4),
        "p95_delta_s": round(p95_delta, 4),
        "max_delta_s": round(float(delta_lat.max()), 4),
        "p95_full_s": round(p95_full, 4),
        "sustained_pods_per_sec": round(arrived / max(float(lat_np.sum()), 1e-9), 1),
        "resident_hit_ratio": round(
            sum(1 for m in modes if m == "delta") / len(modes), 3
        ),
        "modes": {m: modes.count(m) for m in sorted(set(modes))},
        "gate_min_speedup_x": STEADY_MIN_SPEEDUP_X,
        "speedup_x": speedup,
        "gate_ok": speedup >= STEADY_MIN_SPEEDUP_X,
        # critical-path rollup of the most recent full round in the trace
        # (every delta round skips the instrumented path), falling back to
        # the forced full re-solve's own waterfall
        "waterfall": wf_digest
        or _wf_digest(dict(getattr(full_sched, "last_timings", {}) or {})),
        # "rounds" above is the trace length; the ledger digest of the
        # same rounds (mode mix + per-phase p50/p95) rides along under
        # its own key (bench --report-rounds prints it)
        "ledger_rounds": _ledger_rounds_summary(ledger_seq0),
        **envelope,
    }


# the fleet chaos gate: p95 per-delta latency of the surviving replicas
# must stay within this factor of the single-replica steady p95 (the
# handoff round itself is reported separately as handoff_s)
FLEET_MAX_P95_RATIO = 2.0

# the tracing-overhead gate (ISSUE 17): steady-state p95 with fleet trace
# propagation ON must stay within this factor of the same trace with
# propagation OFF (KTPU_FLEET_TRACE=0). The context is four fields and a
# metadata entry, so the honest ratio is ~1.0; the gate absorbs p95
# noise on a 10-round trace
TRACE_OVERHEAD_MAX_RATIO = 1.5


def run_fleet_stage(
    resident_pods=768,
    delta_pods=24,
    rounds=10,
    seed=0,
    kill_round=4,
    max_claims=1024,
    trace_out=None,
):
    """--fleet (ISSUE 16/17): multi-replica chaos under Poisson arrivals.

    Two in-process solver replicas share a guardrail bus; a client runs
    the steady Poisson trace against replica A alone (the latency
    yardstick, fleet tracing on), the same trace again with tracing OFF
    (the overhead gate), then a second client runs it against the "A,B"
    routing front while A is killed mid-stream. The killed replica's
    resident session must hand off to B via the bus's capsule transcript
    (rebuilt fingerprint == the lost chain, counted in
    ktpu_fleet_handoffs_total{outcome="adopted"}), zero rounds may be
    lost, chaos p95 per-delta latency must stay within
    FLEET_MAX_P95_RATIO of the steady p95, and a quarantine trip on A's
    breaker must reach B's within one bus pump.

    The observability acceptance (ISSUE 17) rides the same run: the
    chaos rounds must stitch into fleet traces in which every original
    round appears exactly once, the handoff's trace id must span both
    replicas, the stitched trace must export as valid Perfetto JSON
    (written to ``trace_out`` when given) whose slices reconcile with
    the waterfall invariant, and the ktpu_slo_* availability burn rate
    must reflect the injected kill."""
    import numpy as np

    from karpenter_tpu.envelope.sampler import measured
    from karpenter_tpu.fleet import FleetMember, InProcessHub
    from karpenter_tpu.fleet import bus as bus_mod
    from karpenter_tpu.guard.quarantine import Quarantine
    from karpenter_tpu.models.pod import make_pod
    from karpenter_tpu.obs import fleetobs, traceexport
    from karpenter_tpu.obs import ledger as obs_ledger
    from karpenter_tpu.obs.slo import SLO
    from karpenter_tpu.rpc import client as rpc_client
    from karpenter_tpu.rpc.client import RemoteScheduler
    from karpenter_tpu.rpc.service import SolverService, serve
    from karpenter_tpu.utils.metrics import (
        FLEET_BUS_MESSAGES,
        FLEET_HANDOFFS,
        FLEET_RETARGETS,
    )

    def kind_batch(name, n):
        out = []
        for i in range(n):
            p = make_pod(f"{name}-{i}", cpu=1.0, memory="1Gi")
            p.metadata.labels = {"app": name}
            out.append(p)
        return out

    rng = np.random.default_rng(seed)
    kind_size = 256
    base = []
    for k in range(max(resident_pods // kind_size, 1)):
        base.extend(kind_batch(f"base-{k}", kind_size))
    templates = make_templates(100)

    hub = InProcessHub()
    # distinct Quarantine instances per replica: both replicas live in
    # THIS process, where the global breaker is shared — propagation
    # through the bus would be trivially true without this split
    qa, qb = Quarantine(), Quarantine()
    ma = FleetMember(hub, "bench-a", quarantine=qa)
    mb = FleetMember(hub, "bench-b", quarantine=qb)
    server_a, addr_a = serve(service=SolverService(fleet=ma))
    server_b, addr_b = serve(service=SolverService(fleet=mb))

    outcomes = (
        "adopted", "no_capsule", "fingerprint_mismatch",
        "replay_failed", "shape_mismatch",
    )
    h0 = {o: FLEET_HANDOFFS.get(outcome=o) for o in outcomes}
    rt0 = FLEET_RETARGETS.get(reason="transport") + FLEET_RETARGETS.get(
        reason="circuit_open"
    )
    # fast failover for the bench: one transport retry, short backoff
    saved = (
        rpc_client.TRANSPORT_RETRIES,
        rpc_client.RETRY_BASE_SECONDS,
        rpc_client.RETRY_CAP_SECONDS,
    )
    rpc_client.TRANSPORT_RETRIES = 1
    rpc_client.RETRY_BASE_SECONDS = 0.05
    rpc_client.RETRY_CAP_SECONDS = 0.1
    def steady_trace(client, prefix, trace_rng):
        live: list[list] = []
        lats: list[float] = []
        for rnd in range(rounds):
            live.append(
                kind_batch(
                    f"{prefix}{rnd}", max(int(trace_rng.poisson(delta_pods)), 1)
                )
            )
            union = base + [p for b in live for p in b]
            t0 = time.perf_counter()
            res = client.solve(list(union))
            lats.append(time.perf_counter() - t0)
            assert not res.unschedulable
        return lats

    envelope = {}
    try:
        with measured(envelope, stage=f"fleet_{resident_pods}x{delta_pods}"):
            # phase 1: single-replica steady trace — the latency yardstick
            # (fleet trace propagation on, the default)
            c1 = RemoteScheduler(addr_a, templates, max_claims=max_claims)
            c1.solve(list(base))
            lat_steady = steady_trace(c1, "s", np.random.default_rng(seed))
            # phase 1b: the identical trace with propagation OFF — the
            # tracing-overhead gate's denominator (same shapes, so the
            # compile caches are warm for both passes)
            trace_env0 = os.environ.get("KTPU_FLEET_TRACE")
            os.environ["KTPU_FLEET_TRACE"] = "0"
            try:
                c_off = RemoteScheduler(addr_a, templates, max_claims=max_claims)
                c_off.solve(list(base))
                lat_off = steady_trace(c_off, "o", np.random.default_rng(seed))
            finally:
                if trace_env0 is None:
                    os.environ.pop("KTPU_FLEET_TRACE", None)
                else:
                    os.environ["KTPU_FLEET_TRACE"] = trace_env0
            # phase 2: the same trace against the A,B front; A dies
            # mid-stream and its session must hand off to B
            chaos_seq0 = obs_ledger.LEDGER.seq()
            c2 = RemoteScheduler(
                f"{addr_a},{addr_b}", templates, max_claims=max_claims
            )
            c2.solve(list(base))
            live2: list[list] = []
            lat_chaos: list[float] = []
            killed, handoff_s, solved = False, None, 0
            for rnd in range(rounds):
                if rnd == kill_round:
                    server_a.stop(0)
                    killed = True
                live2.append(
                    kind_batch(f"c{rnd}", max(int(rng.poisson(delta_pods)), 1))
                )
                union = base + [p for b in live2 for p in b]
                t0 = time.perf_counter()
                res = c2.solve(list(union))
                dt = time.perf_counter() - t0
                assert not res.unschedulable, f"chaos round {rnd} lost pods"
                solved += 1
                if killed and handoff_s is None:
                    handoff_s = dt  # the failover round: retarget + adopt
                else:
                    lat_chaos.append(dt)
            # fleet-wide quarantine: trip A's breaker, B must observe it
            # within one pump (== one solve round)
            qa.trip("resident", reason="bench-chaos")
            mb.pump()
            quarantine_propagated = qb.active("resident")
    finally:
        (
            rpc_client.TRANSPORT_RETRIES,
            rpc_client.RETRY_BASE_SECONDS,
            rpc_client.RETRY_CAP_SECONDS,
        ) = saved
        for srv in (server_a, server_b):
            try:
                srv.stop(0)
            except Exception:
                pass
        ma.close()
        mb.close()
    handoffs = {
        o: int(FLEET_HANDOFFS.get(outcome=o) - h0[o]) for o in outcomes
    }
    assert handoffs["adopted"] >= 1, f"no session adopted: {handoffs}"
    assert quarantine_propagated, "quarantine trip did not cross the bus"
    p95_steady = float(np.percentile(np.asarray(lat_steady), 95))
    p95_chaos = float(np.percentile(np.asarray(lat_chaos), 95))
    ratio = round(p95_chaos / p95_steady, 2) if p95_steady > 0 else float("inf")
    p95_off = float(np.percentile(np.asarray(lat_off), 95))
    trace_ratio = (
        round(p95_steady / p95_off, 2) if p95_off > 0 else float("inf")
    )
    # -- fleet observatory acceptance (ISSUE 17) ---------------------------
    # stitch the chaos phase: every original round exactly once, the
    # handoff trace spanning both replicas, and a valid Perfetto export
    chaos_recs = [
        r for r in fleetobs.fleet_records(dirs=[])
        if (r.get("seq") or 0) > chaos_seq0
    ]
    counts = fleetobs.round_counts(chaos_recs)
    dup = {s: n for s, n in counts.items() if n != 1}
    assert not dup, f"rounds stitched more than once: {dup}"
    replays = [r for r in chaos_recs if r.get("replay")]
    assert replays, "adoption left no replay-marked rounds to stitch"
    handoff_trace = (replays[0].get("trace") or {}).get("id")
    stitched = fleetobs.stitch(handoff_trace, chaos_recs)
    assert stitched is not None and len(stitched["replicas"]) >= 2, (
        f"handoff trace {handoff_trace} does not span both replicas: "
        f"{stitched and stitched['replicas']}"
    )
    perfetto = traceexport.chrome_trace(chaos_recs)
    perfetto_problems = traceexport.validate(
        json.loads(json.dumps(perfetto))
    )
    assert not perfetto_problems, f"perfetto export invalid: {perfetto_problems}"
    if trace_out:
        with open(trace_out, "w") as fh:
            json.dump(perfetto, fh, sort_keys=True)
    slo = SLO.snapshot()
    avail_5m = slo["burn_rates"]["availability"]["5m"]
    assert avail_5m["bad"] >= 1, (
        f"the injected kill left no availability burn: {avail_5m}"
    )
    telemetry_frames = int(
        FLEET_BUS_MESSAGES.get(topic="telemetry", direction="published")
    )
    return {
        "resident_pods": len(base),
        "delta_pods": delta_pods,
        "rounds": rounds,
        "seed": seed,
        "kill_round": kill_round,
        "rounds_lost": rounds - solved,
        "p95_steady_s": round(p95_steady, 4),
        "p95_chaos_s": round(p95_chaos, 4),
        "handoff_s": round(handoff_s, 4) if handoff_s is not None else None,
        "p95_ratio": ratio,
        "gate_max_ratio": FLEET_MAX_P95_RATIO,
        "gate_ok": ratio <= FLEET_MAX_P95_RATIO,
        "handoffs": handoffs,
        "retargets": int(
            FLEET_RETARGETS.get(reason="transport")
            + FLEET_RETARGETS.get(reason="circuit_open")
            - rt0
        ),
        "quarantine_propagated": quarantine_propagated,
        "bus_published": int(
            sum(
                FLEET_BUS_MESSAGES.get(topic=t, direction="published")
                for t in bus_mod.TOPICS
            )
        ),
        "telemetry_frames": telemetry_frames,
        # -- tracing-overhead gate (ISSUE 17): steady p95 with fleet trace
        # propagation on vs off, ratcheted by obs/bench_diff.py
        "p95_trace_on_s": round(p95_steady, 4),
        "p95_trace_off_s": round(p95_off, 4),
        "trace_overhead_ratio": trace_ratio,
        "trace_gate_max_ratio": TRACE_OVERHEAD_MAX_RATIO,
        "trace_gate_ok": trace_ratio <= TRACE_OVERHEAD_MAX_RATIO,
        "trace": {
            "trace_id": handoff_trace,
            "replicas": stitched["replicas"],
            "rounds": len(stitched["rounds"]),
            "replays": stitched["replays"],
            "max_hop": stitched["max_hop"],
            "unique_ok": not dup,
            "perfetto_events": len(perfetto["traceEvents"]),
            "perfetto_ok": not perfetto_problems,
        },
        "slo": {
            "target": slo["target"],
            "burn_rates": slo["burn_rates"],
            "budget_remaining": slo["budget_remaining"],
        },
        **envelope,
    }


def run_whatif_stage(n_candidates, seq_sample=8):
    """Batched vs sequential consolidation what-ifs (the §2.6 tensorization:
    one vmapped dispatch vs N sequential re-solves)."""
    from karpenter_tpu.envelope.sampler import measured
    from karpenter_tpu.testing import FakeCandidate, build_bound_cluster

    envelope = {}
    with measured(envelope, stage=f"whatif_{n_candidates}"):
        _clock, store, _cloud, mgr = build_bound_cluster(
            n_pods=n_candidates, pod_cpu=2.0
        )
        by_node: dict[str, list] = {}
        for p in store.pods():
            if p.spec.node_name:
                by_node.setdefault(p.spec.node_name, []).append(p)
        candidates = [
            FakeCandidate(name, pods) for name, pods in sorted(by_node.items())
        ]
        scenarios = [[c] for c in candidates]
        prov = mgr.provisioner
        warm = prov.simulate_batch(scenarios)
        assert warm is not None, "batch path gated"
        prov.simulate({candidates[0].name}, candidates[0].reschedulable_pods)
        t0 = time.perf_counter()
        signals = prov.simulate_batch(scenarios)
        t_batch = time.perf_counter() - t0
        t0 = time.perf_counter()
        for c in candidates[:seq_sample]:
            prov.simulate({c.name}, c.reschedulable_pods)
        t_seq = (time.perf_counter() - t0) * (len(candidates) / seq_sample)
    speedup = round(t_seq / t_batch, 1) if t_batch > 0 else float("inf")
    return {
        "candidates": len(candidates),
        "batch_s": round(t_batch, 3),
        "sequential_s_extrapolated": round(t_seq, 3),
        "speedup_x": speedup,
        "gate_min_speedup_x": WHATIF_MIN_SPEEDUP_X,
        "gate_ok": speedup >= WHATIF_MIN_SPEEDUP_X,
        "feasible": sum(1 for ok, _ in signals if ok),
        **envelope,
    }


def run_objective_stage(n_pods=192, n_types=48) -> dict:
    """Placement objectives (ISSUE 19): ONE mixed-generation multi-pool
    problem (four family-restricted pools, priciest family holding the
    lexical weight order) solved under every registered policy, reporting
    each policy's fleet ``total_price_per_hour`` and solve wall. The
    per-policy ``solve_s`` leaves ride the normal ``--baseline`` ratchet
    (obs/bench_diff diffs every ``_s`` leaf); the PRICE gate is enforced
    right here: ``cost_min`` must never produce a pricier fleet than
    ``lexical`` on this stage — that is the objective's whole claim."""
    import os

    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.controllers.provisioning import (
        TPUScheduler,
        build_templates,
    )
    from karpenter_tpu.models.nodepool import NodePool
    from karpenter_tpu.objectives import POLICIES
    from karpenter_tpu.objectives import oracle as obj_oracle

    def pool_templates():
        catalog = instance_types(n_types)
        pools = []
        # priciest family first: lexical's weight order picks the 1.2x
        # "m" nodes, so cost_min has a real gap to close (e = 0.6x)
        for fam in ("m", "s", "c", "e"):
            p = NodePool()
            p.metadata.name = f"{fam}-pool"
            p.spec.template.spec.requirements = [
                {
                    "key": "karpenter-tpu.sh/instance-family",
                    "operator": "In",
                    "values": [fam],
                },
            ]
            pools.append((p, catalog))
        return build_templates(pools)

    out: dict = {"pods": n_pods, "types": n_types, "policies": {}}
    prev = os.environ.get("KTPU_OBJECTIVE")
    try:
        for pol in POLICIES:
            os.environ["KTPU_OBJECTIVE"] = pol
            sched = TPUScheduler(
                pool_templates(), pod_pad=n_pods, max_claims=256
            )
            t0 = time.perf_counter()
            result = sched.solve(mixed_pods(n_pods))
            wall = time.perf_counter() - t0
            assert not result.unschedulable, (
                f"{pol}: {len(result.unschedulable)} unschedulable"
            )
            out["policies"][pol] = {
                "solve_s": round(wall, 4),
                "nodes": len(result.claims),
                "total_price_per_hour": round(
                    obj_oracle.total_price_per_hour(result), 5
                ),
            }
    finally:
        if prev is None:
            os.environ.pop("KTPU_OBJECTIVE", None)
        else:
            os.environ["KTPU_OBJECTIVE"] = prev
    lex = out["policies"]["lexical"]["total_price_per_hour"]
    cmin = out["policies"]["cost_min"]["total_price_per_hour"]
    out["cost_gate"] = {
        "lexical_price_per_hour": lex,
        "cost_min_price_per_hour": cmin,
        "ok": cmin <= lex + 1e-6,
    }
    assert out["cost_gate"]["ok"], (
        f"cost_min produced a PRICIER fleet than lexical: {cmin} > {lex}"
    )
    return out


def run_gang_storm_stage(on_tpu: bool) -> dict:
    """Gang-storm (ISSUE 6): a training-job burst — all-or-nothing gangs
    mixed with singleton pods, plus one deliberately unplaceable "whale"
    gang — through the full pipeline. Reports gangs-scheduled/sec and the
    spill count (the whale must spill atomically: every member fails
    together, nothing else is disturbed)."""
    from karpenter_tpu.controllers.provisioning import TPUScheduler
    from karpenter_tpu.envelope.sampler import measured
    from karpenter_tpu.gang import make_gang_pods

    n_gangs, gang_size, n_singles, n_types, max_claims = (
        (64, 16, 2048, 400, 2048) if on_tpu else (12, 8, 256, 100, 256)
    )
    pods = []
    for gi in range(n_gangs):
        pods.extend(make_gang_pods(f"storm-{gi}", gang_size, cpu=1.5))
    # the whale: no instance type can host a member -> atomic spill
    pods.extend(make_gang_pods("whale", 4, cpu=10000.0))
    pods.extend(selector_pods(n_singles))
    envelope = {}
    with measured(envelope, stage="gang_storm"):
        templates = make_templates(n_types)
        sched = TPUScheduler(templates, pod_pad=len(pods), max_claims=max_claims)
        t0 = time.perf_counter()
        result = sched.solve(pods)  # cold
        cold_s = time.perf_counter() - t0
        best = None
        for _ in range(2):
            t0 = time.perf_counter()
            result = sched.solve(pods)
            wall = time.perf_counter() - t0
            best = wall if best is None or wall < best else best
        best = best if best is not None else cold_s
    # every spill is a WHOLE gang: unschedulable members group exactly
    # into complete gangs (here: just the whale)
    from karpenter_tpu.gang import gang_of

    spilled: dict[str, int] = {}
    for p, _reason in result.unschedulable:
        parsed = gang_of(p)
        assert parsed is not None, f"singleton spilled: {p.metadata.name}"
        spilled[parsed[0]] = spilled.get(parsed[0], 0) + 1
    assert spilled == {"default/whale": 4}, f"partial spill: {spilled}"
    slice_hosts = sum(1 for c in result.claims if c.gang)
    return {
        "gangs": n_gangs,
        "gang_size": gang_size,
        "singles": n_singles,
        "pods": len(pods),
        "wall_s": round(best, 4),
        "cold_s": round(cold_s, 2),
        "gangs_per_sec": round(n_gangs / best, 1),
        "pods_per_sec": round(len(pods) / best, 1),
        "slice_hosts": slice_hosts,
        "spilled_gangs": len(spilled),
        "spilled_pods": sum(spilled.values()),
        **envelope,
    }


def run_restart_stage(n_pods, n_types, max_claims, on_tpu=True):
    """Cold-start cost after a process restart with the persistent compile
    cache populated (the bench process itself just populated it): the
    number that must stay inside the reference's 1m Solve window."""
    import subprocess
    import sys

    child = (
        "import json, time, sys; sys.path.insert(0, '.');\n"
        + (
            ""
            if on_tpu
            else "from karpenter_tpu.utils.accel import force_cpu; force_cpu()\n"
        )
        + "from bench import selector_pods, make_templates\n"
        "from karpenter_tpu.controllers.provisioning import TPUScheduler\n"
        # the child reports ITS OWN envelope — the restart cost in memory,
        # not just wall (read post-solve, so the compile peak is included)
        "from karpenter_tpu.envelope.sampler import read_cpu_seconds, read_rss_bytes\n"
        f"pods = selector_pods({n_pods})\n"
        f"sched = TPUScheduler(make_templates({n_types}), pod_pad={n_pods}, max_claims={max_claims})\n"
        "t0 = time.perf_counter(); r = sched.solve(pods)\n"
        "print(json.dumps({'cold_s': round(time.perf_counter() - t0, 2),\n"
        "                  'host_rss_mb': round(read_rss_bytes() / 2**20, 1),\n"
        "                  'cpu_s': round(read_cpu_seconds(), 3)}))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", child], capture_output=True, text=True, timeout=900
    )
    if out.returncode != 0:
        return f"failed: {out.stderr[-200:]}"
    return json.loads(out.stdout.strip().splitlines()[-1])


def run_shard_stage(n_pods=8192, n_types=200, max_claims=2048):
    """Default-bench per-shard stage (ISSUE 8): a subprocess forces an
    8-virtual-device CPU mesh (XLA_FLAGS) + the KTPU_MESH=2x4 override so
    the (dp × it) shard path runs — and its last_timings["shard"] record
    lands in the bench JSON — even on hosts without an accelerator. The
    child also pins the meshed solve node-count/price-identical to the
    single-device solve (the cheap in-bench parity tripwire; the full
    bit-parity suites are tests/test_shard.py + tests/test_mesh_parity.py).
    """
    import os
    import subprocess
    import sys

    child = (
        "import json, os, time, sys; sys.path.insert(0, '.');\n"
        "flags = os.environ.get('XLA_FLAGS', '')\n"
        "if 'xla_force_host_platform_device_count' not in flags:\n"
        "    os.environ['XLA_FLAGS'] = (flags + ' --xla_force_host_platform_device_count=8').strip()\n"
        "os.environ['KTPU_MESH'] = '2x4'\n"
        "os.environ['KTPU_PIPELINE_MIN_PODS'] = '1024'\n"
        "from karpenter_tpu.utils.accel import force_cpu; force_cpu()\n"
        "from bench import selector_pods, zonal_pods, make_templates, _wf_digest\n"
        "from karpenter_tpu.controllers.provisioning import TPUScheduler\n"
        "from karpenter_tpu.parallel import make_mesh\n"
        f"pods = selector_pods({n_pods})\n"
        f"single = TPUScheduler(make_templates({n_types}), pod_pad={n_pods}, max_claims={max_claims}).solve(pods)\n"
        f"sched = TPUScheduler(make_templates({n_types}), pod_pad={n_pods}, max_claims={max_claims}, mesh=make_mesh())\n"
        "sched.solve(pods)  # cold (compile)\n"
        "t0 = time.perf_counter(); r = sched.solve(pods)\n"
        "wall = time.perf_counter() - t0\n"
        "assert r.assignments == single.assignments, 'meshed != single-device'\n"
        "# a zonal-only twin solve exercises the kscan dp-speculative path\n"
        "# (mixing it into the main solve would make the whole problem\n"
        "# topology-bearing and disqualify FILL speculation)\n"
        "zpods = zonal_pods(512, kinds=8)\n"
        "os.environ['KTPU_PIPELINE_MIN_PODS'] = '256'  # the twin is small\n"
        f"zsingle = TPUScheduler(make_templates({n_types}), pod_pad=512).solve(zpods)\n"
        f"zsched = TPUScheduler(make_templates({n_types}), pod_pad=512, mesh=make_mesh())\n"
        "zr = zsched.solve(zpods)\n"
        "assert zr.assignments == zsingle.assignments, 'kscan meshed != single-device'\n"
        "from karpenter_tpu.utils.metrics import SHARD_MERGE_ROUNDS\n"
        "kscan_rounds = sum(SHARD_MERGE_ROUNDS.get(outcome=o, family='kscan')\n"
        "                   for o in ('committed', 'replayed'))\n"
        "assert kscan_rounds > 0, 'kscan family never took the dp path'\n"
        "# ISSUE 14 twins: the three previously sequential-only stateful\n"
        "# families (existing-node debits, topology-bearing fill, per-pod\n"
        "# runs) must each speculate AND commit at least one dp round\n"
        "from bench import existing_sim_nodes, hostname_pods, perpod_pods\n"
        "from karpenter_tpu.models.pod import make_pod\n"
        "hpods = hostname_pods(512, kinds=8)\n"
        f"hsingle = TPUScheduler(make_templates({n_types}), pod_pad=512).solve(hpods)\n"
        f"hsched = TPUScheduler(make_templates({n_types}), pod_pad=512, mesh=make_mesh())\n"
        "hr = hsched.solve(hpods)\n"
        "assert hr.assignments == hsingle.assignments, 'topo_fill meshed != single-device'\n"
        "epods = []\n"
        "for i in range(512):\n"
        "    p = make_pod(f'eb-{i}', cpu=2.0, memory='1Gi')\n"
        "    p.metadata.labels = {'grp': str(i // 64)}\n"
        "    epods.append(p)\n"
        f"esingle = TPUScheduler(make_templates({n_types}), pod_pad=512).solve(list(epods), existing_sim_nodes())\n"
        f"esched = TPUScheduler(make_templates({n_types}), pod_pad=512, mesh=make_mesh())\n"
        "er = esched.solve(list(epods), existing_sim_nodes())\n"
        "assert er.assignments == esingle.assignments, 'existing meshed != single-device'\n"
        "assert er.existing_assignments == esingle.existing_assignments, 'existing debits diverged'\n"
        "os.environ['KTPU_SOLVE_CHUNK'] = '128'  # 512 pods -> 4 per-pod chunks\n"
        "ppods = perpod_pods(512, kinds=8)\n"
        f"psingle = TPUScheduler(make_templates({n_types}), pod_pad=512).solve(ppods)\n"
        f"psched = TPUScheduler(make_templates({n_types}), pod_pad=512, mesh=make_mesh())\n"
        "pr = psched.solve(ppods)\n"
        "os.environ.pop('KTPU_SOLVE_CHUNK', None)\n"
        "assert pr.assignments == psingle.assignments, 'perpod meshed != single-device'\n"
        "# ISSUE 20 rung-1 twin: enforced minValues + finite disruption\n"
        "# budgets no longer disqualify perpod-dp — debits ride the slice\n"
        "# as order-free deltas behind the disjointness verdict bit; must\n"
        "# commit >=1 dp round AND stay identical to the single-device\n"
        "# solve and the host oracle\n"
        "from bench import mv_templates, host_solve\n"
        "os.environ['KTPU_SOLVE_CHUNK'] = '128'\n"
        "bpods = perpod_pods(512, kinds=8, prefix='bb')\n"
        "budgets = {'default': {'cpu': 1e6}}\n"
        "committed0 = SHARD_MERGE_ROUNDS.get(outcome='committed', family='perpod')\n"
        f"bsingle = TPUScheduler(mv_templates({n_types}), pod_pad=512).solve(bpods, budgets={{'default': dict(budgets['default'])}})\n"
        f"bsched = TPUScheduler(mv_templates({n_types}), pod_pad=512, mesh=make_mesh())\n"
        "br = bsched.solve(bpods, budgets={'default': dict(budgets['default'])})\n"
        "os.environ.pop('KTPU_SOLVE_CHUNK', None)\n"
        "budget_committed = int(SHARD_MERGE_ROUNDS.get(outcome='committed', family='perpod') - committed0)\n"
        "assert budget_committed >= 1, 'perpod under mv+budgets never committed a dp round'\n"
        "assert br.assignments == bsingle.assignments, 'perpod mv+budget meshed != single-device'\n"
        f"bhost, _ = host_solve(mv_templates({n_types}), bpods, budgets={{'default': dict(budgets['default'])}})\n"
        "assert br.assignments == bhost.assignments, 'perpod mv+budget meshed != host oracle'\n"
        "# ISSUE 20 rung-2 twin: gang x zonal-spread stays on device (one\n"
        "# vg evaluation per rank block inside the gang kernel) with zero\n"
        "# gang_constraints fallbacks, host-oracle identical; the zonal\n"
        "# singles in the same solve keep dp-speculating via kscan\n"
        "from karpenter_tpu.gang import make_gang_pods\n"
        "from karpenter_tpu.models import labels as l\n"
        "from karpenter_tpu.models.pod import TopologySpreadConstraint\n"
        "from karpenter_tpu.utils.metrics import SOLVER_FALLBACK\n"
        "gfall0 = SOLVER_FALLBACK.get(reason='gang_constraints')\n"
        "gang = make_gang_pods('bgz', 6, cpu=1.0)\n"
        "for p in gang:\n"
        "    p.metadata.labels = dict(p.metadata.labels or {}, spread='bgz')\n"
        "    p.spec.topology_spread_constraints = [TopologySpreadConstraint(\n"
        "        max_skew=1, topology_key=l.LABEL_TOPOLOGY_ZONE,\n"
        "        label_selector={'spread': 'bgz'})]\n"
        "os.environ['KTPU_PIPELINE_MIN_PODS'] = '64'\n"
        "gpods = gang + zonal_pods(192, kinds=8, prefix='bgz')\n"
        f"gsched = TPUScheduler(make_templates({n_types}), pod_pad=256, mesh=make_mesh())\n"
        "gr = gsched.solve(gpods)\n"
        "gang_fallbacks = int(SOLVER_FALLBACK.get(reason='gang_constraints') - gfall0)\n"
        "assert gang_fallbacks == 0, 'gang+zonal raised _GangHostRoute'\n"
        f"ghost, _ = host_solve(make_templates({n_types}), gpods)\n"
        "assert gr.assignments == ghost.assignments, 'gang+zonal meshed != host oracle'\n"
        "fam_committed = {}\n"
        "for fam in ('fill', 'existing', 'topo_fill', 'kscan', 'perpod'):\n"
        "    fam_committed[fam] = SHARD_MERGE_ROUNDS.get(outcome='committed', family=fam)\n"
        "for fam in ('existing', 'topo_fill', 'perpod'):\n"
        "    assert fam_committed[fam] > 0, f'{fam} family never committed a dp merge round'\n"
        "# per-family routing coverage across every meshed solve above —\n"
        "# the measured speculation coverage --report-shard prints.\n"
        "# sum(), not get(): sequential increments carry a reason label\n"
        "# naming the failed conjunct, so the exact-key get() misses them\n"
        "from karpenter_tpu.utils.metrics import SHARD_FAMILY_ELIGIBLE\n"
        "coverage = {f: {'dp': int(SHARD_FAMILY_ELIGIBLE.sum(family=f, path='dp')),\n"
        "                'sequential': int(SHARD_FAMILY_ELIGIBLE.sum(family=f, path='sequential'))}\n"
        "            for f in ('fill', 'existing', 'topo_fill', 'kscan', 'perpod', 'gang')}\n"
        "print(json.dumps({'wall_s': round(wall, 4),\n"
        "                  'pods_per_sec': round(len(pods) / wall, 1),\n"
        "                  'nodes': r.node_count,\n"
        "                  'parity_vs_single_device': True,\n"
        "                  'kscan_merge_rounds_total': kscan_rounds,\n"
        "                  'family_committed': fam_committed,\n"
        "                  'budget_committed_rounds': budget_committed,\n"
        "                  'gang_fallbacks': gang_fallbacks,\n"
        "                  'coverage': coverage,\n"
        "                  'shard': sched.last_timings.get('shard'),\n"
        "                  'waterfall': _wf_digest(sched.last_timings),\n"
        "                  'waterfall_kscan': _wf_digest(zsched.last_timings),\n"
        "                  'shard_kscan': zsched.last_timings.get('shard')}))\n"
    )
    env = dict(os.environ)
    env.pop("KTPU_SCAN_WINDOW", None)
    out = subprocess.run(
        [sys.executable, "-c", child],
        capture_output=True,
        text=True,
        timeout=900,
        env=env,
    )
    if out.returncode != 0:
        return f"failed: {out.stderr[-300:]}"
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    rec["pods"] = n_pods
    rec["types"] = n_types
    # per-family dp coverage fraction on the record (ISSUE 20 satellite):
    # bench_diff ratchets a >=0.05 DECREASE as a regression. Zero-routed
    # families are skipped, not recorded as 0 — a family the run never
    # routed has no coverage to regress
    cov = rec.get("coverage") or {}
    rec["coverage_fraction"] = {
        f: round(v["dp"] / (v["dp"] + v["sequential"]), 4)
        for f, v in cov.items()
        if v["dp"] + v["sequential"] > 0
    }
    return rec


def run_1m_stage(on_tpu: bool, mesh=None) -> dict:
    """northstar_1000000x1000 (ISSUE 8): the 1M-pod × 1000-type scale
    probe the ROADMAP names — the (dp × it) mesh makes it a per-shard
    problem (pipelined fill chunk groups solve one-per-dp-row; committed
    claims become frozen decode-only rows other shards constrain against).
    TPU-gated — the un-accelerated 1M scan takes tens of minutes on CPU —
    but KTPU_BENCH_1M=1 forces it for offline runs. warm_runs=1: one
    cold + one steady-state solve is already ~minutes of device time at
    this scale."""
    return run_stage(
        selector_pods(1_000_000), 1000, 65536, warm_runs=1, mesh=mesh
    )


def run_rpc_stage(pods, n_types, local_wall_s):
    """The control/solver gRPC split's overhead: the same warm solve
    through an in-process server on loopback (SURVEY §2.9; rpc/)."""
    from karpenter_tpu.envelope.sampler import measured
    from karpenter_tpu.rpc import RemoteScheduler, serve

    envelope = {}
    server, addr = serve("127.0.0.1:0")
    try:
        with measured(envelope, stage=f"rpc_{len(pods)}x{n_types}"):
            remote = RemoteScheduler(addr, make_templates(n_types))
            remote.solve(pods)  # warm (server-side compile reuses the cache)
            best = None
            for _ in range(2):
                t0 = time.perf_counter()
                result = remote.solve(pods)
                wall = time.perf_counter() - t0
                best = wall if best is None or wall < best else best
            assert not result.unschedulable
        return {
            "wall_s": round(best, 4),
            "overhead_ms": round((best - local_wall_s) * 1000.0, 1),
            "pods_per_sec": round(len(pods) / best, 1),
            **envelope,
        }
    finally:
        server.stop(0)


def run_chaos_stage(on_tpu: bool) -> dict:
    """--chaos smoke: the north-star scenario under a LIGHT fault plan
    (occasional injected latency at the device-dispatch seam plus one
    recovered device failure), asserting the wall-clock gate still holds
    and that the fault points' disabled-path overhead is < 1% of a solve.

    On TPU the workload and gate are the north star's
    (tests/test_perf_gate.NORTHSTAR_MAX_WALL_S); the CPU fallback runs
    the 2048-selector stage and gates only the overhead + convergence
    halves (there is no CPU wall gate to hold)."""
    from karpenter_tpu.controllers.provisioning import TPUScheduler
    from karpenter_tpu.faultinject import FAULT, FaultInjector, active_plan

    n_pods, n_types, max_claims = (100_000, 1000, 4096) if on_tpu else (2048, 400, 256)
    # test_perf_gate.NORTHSTAR_MAX_WALL_S (0.45) + chaos-plan headroom
    wall_gate_s = 0.55 if on_tpu else None
    pods = selector_pods(n_pods)
    templates = make_templates(n_types)
    sched = TPUScheduler(templates, pod_pad=n_pods, max_claims=max_claims)
    baseline = sched.solve(pods)  # cold
    t0 = time.perf_counter()
    baseline = sched.solve(pods)
    clean_wall = time.perf_counter() - t0
    assert not baseline.unschedulable

    # 1. disabled-path overhead: a solve crosses a handful of fault
    # points; budget 1000 crossings and demand they cost < 1% of the
    # measured clean solve (the same discipline as the tracer gate)
    probe = FaultInjector()  # disabled: the production steady state
    n_calls = 100_000
    t0 = time.perf_counter()
    for _ in range(n_calls):
        probe.point("bench.overhead")
    per_call_s = (time.perf_counter() - t0) / n_calls
    overhead_frac = (per_call_s * 1000) / clean_wall
    assert overhead_frac < 0.01, (
        f"disabled fault points cost {100 * overhead_frac:.2f}% of a solve"
    )

    # 2. the light plan: rare 1ms latency at the dispatch seam + exactly
    # one injected device failure (absorbed by the degradation ladder)
    plan = {
        "seed": 97,
        "rules": [
            {"point": "solver.dispatch", "error": "runtime", "times": 1},
            {"point": "solver.dispatch", "mode": "latency", "delay_s": 0.001, "p": 0.25},
        ],
    }
    with active_plan(plan):
        degraded = sched.solve(pods)  # the device failure -> host oracle
        t0 = time.perf_counter()
        chaotic = sched.solve(pods)  # back on the device, latency plan live
        chaos_wall = time.perf_counter() - t0
        injected = FAULT.fires()
    assert not degraded.unschedulable and not chaotic.unschedulable
    assert chaotic.node_count == baseline.node_count, "chaos changed the answer"
    out = {
        "pods": n_pods,
        "types": n_types,
        "clean_wall_s": round(clean_wall, 4),
        "chaos_wall_s": round(chaos_wall, 4),
        "faults_injected": injected,
        "disabled_point_ns": round(per_call_s * 1e9, 1),
        "disabled_overhead_frac_of_solve": round(overhead_frac, 6),
    }
    if wall_gate_s is not None:
        out["wall_gate_s"] = wall_gate_s
        out["gate_ok"] = chaos_wall <= wall_gate_s
        assert out["gate_ok"], (
            f"north-star wall gate broke under the light fault plan: "
            f"{chaos_wall:.3f}s > {wall_gate_s}s"
        )
    return out


def run_guard_stage(on_tpu: bool) -> dict:
    """--guard (ISSUE 10): the guardrails cost model, in two halves.

    1. Disabled steady state (audit rate 0, the production default): a
       solve crosses a handful of ``should_audit`` gates; budget 1000
       crossings and demand they cost < 1% of a measured clean solve —
       the same discipline as the fault-point and tracer overhead gates.
       Hard-asserted.
    2. Paid path (rate 1.0): one resident delta round under a forced
       shadow audit. The exact twin is a cold full re-solve, so its cost
       is REPORTED (twin_s vs the audited round's wall), not gated —
       operators pick a production KTPU_GUARD_AUDIT_RATE from these two
       numbers.
    """
    import os

    from karpenter_tpu import guard
    from karpenter_tpu.controllers.provisioning import TPUScheduler
    from karpenter_tpu.guard import config as guard_config
    from karpenter_tpu.models.pod import make_pod

    def kind_batch(name, n):
        out = []
        for i in range(n):
            p = make_pod(f"{name}-{i}", cpu=1.0, memory="1Gi")
            p.metadata.labels = {"app": name}
            out.append(p)
        return out

    n_pods, n_types, max_claims = (
        (16384, 400, 8192) if on_tpu else (2048, 100, 1024)
    )
    kind_size = 256
    base = []
    for k in range(max(n_pods // kind_size, 1)):
        base.extend(kind_batch(f"base-{k}", kind_size))
    os.environ.pop("KTPU_GUARD_AUDIT_RATE", None)
    guard.QUARANTINE.reset()
    guard.reset_log()
    sched = TPUScheduler(make_templates(n_types), max_claims=max_claims)
    sched.solve(list(base))  # cold compile
    t0 = time.perf_counter()
    baseline = sched.solve(list(base))
    clean_wall = time.perf_counter() - t0
    assert not baseline.unschedulable

    # 1. the disabled gate: rate 0 short-circuits before any RNG draw
    n_calls = 100_000
    t0 = time.perf_counter()
    for _ in range(n_calls):
        guard_config.should_audit("resident")
    per_call_s = (time.perf_counter() - t0) / n_calls
    overhead_frac = (per_call_s * 1000) / clean_wall
    assert overhead_frac < 0.01, (
        f"disabled should_audit gates cost {100 * overhead_frac:.2f}% of a solve"
    )

    # 1b. the always-on flight recorder (ISSUE 12): recording a round is
    # dict assembly + a deque append, no I/O with spill unset. Same
    # discipline as the gates above: budget 1000 records and demand they
    # cost < 1% of a solve (in reality one solve = ONE record, so the
    # production margin is ~1000x wider than the assertion).
    from karpenter_tpu.obs import ledger as obs_ledger

    os.environ.pop(obs_ledger.ENV_DIR, None)
    probe_ledger = obs_ledger.RoundLedger()
    rec_template = {
        "mode": "delta", "reason": "delta", "outcome": "ok", "pods": 64,
        "wall_s": 0.01, "fallback": None, "sig": "00" * 8, "fpr": "11" * 8,
    }
    t0 = time.perf_counter()
    for _ in range(n_calls):
        probe_ledger.record(dict(rec_template))
    ledger_per_call_s = (time.perf_counter() - t0) / n_calls
    ledger_overhead_frac = (ledger_per_call_s * 1000) / clean_wall
    assert ledger_overhead_frac < 0.01, (
        f"round-ledger records cost {100 * ledger_overhead_frac:.2f}% of a "
        "solve per 1000 — too hot for an always-on flight recorder"
    )

    # 1c. the waterfall recorder (ISSUE 15): a recorded span is two
    # perf_counter stamps plus a few list appends; finalize is a small
    # sort, which dominates (~tens of us per round). One solve records
    # exactly ONE round, so the honest budget is per-round: demand 10
    # recorded rounds — each a representative tree of nested spans +
    # externally-timed leaves — cost < 1% of a solve, i.e. the round a
    # solve actually records costs < 0.1%. Hard-asserted like 1 and 1b.
    from karpenter_tpu.obs import waterfall as obs_waterfall

    wf_calls = 10_000
    t0 = time.perf_counter()
    for _ in range(wf_calls):
        wf = obs_waterfall.RoundWaterfall()
        with wf.span("topology"):
            pass
        with wf.span("encode"):
            pass
        with wf.span("dispatch"):
            with wf.span("dispatch.fill_dp"):
                wf.add("enqueue.solve_fill_dp", 1e-4)
                wf.add("fill_dp.device", 1e-4)
                wf.add("fill_dp.sync_verdict", 1e-4)
                wf.add("fill_dp.graft", 1e-4)
        with wf.span("decode"):
            wf.add("wire", 1e-4)
        wf.finalize(wall_s=1e-3)
    wf_per_round_s = (time.perf_counter() - t0) / wf_calls
    wf_overhead_frac = (wf_per_round_s * 10) / clean_wall
    assert wf_overhead_frac < 0.01, (
        f"waterfall recording costs {100 * wf_overhead_frac:.2f}% of a "
        "solve per 10 rounds — too hot for an always-on instrument"
    )

    # 2. the paid path: a resident session takes one delta round with the
    # audit forced on; the twin cost comes out of last_timings
    session = sched.resident_session()
    session.solve(list(base))
    assert session.last_mode == "full"
    os.environ["KTPU_GUARD_AUDIT_RATE"] = "1.0"
    try:
        delta = kind_batch("delta-audited", 64)
        t0 = time.perf_counter()
        result = session.solve(list(base + delta))
        audited_wall = time.perf_counter() - t0
    finally:
        os.environ.pop("KTPU_GUARD_AUDIT_RATE", None)
    assert not result.unschedulable
    stats = session.last_timings["resident"]
    assert stats["mode"] == "delta", stats["reason"]
    assert stats["audit"]["verdict"] == "pass", stats["audit"]
    verdicts: dict = {}
    for rec in guard.AUDIT_LOG:
        key = f"{rec['path']}:{rec['verdict']}"
        verdicts[key] = verdicts.get(key, 0) + 1
    return {
        "pods": n_pods,
        "types": n_types,
        "clean_wall_s": round(clean_wall, 4),
        "disabled_gate_ns": round(per_call_s * 1e9, 1),
        "disabled_overhead_frac_of_solve": round(overhead_frac, 6),
        "ledger_record_ns": round(ledger_per_call_s * 1e9, 1),
        "ledger_overhead_frac_of_solve": round(ledger_overhead_frac, 6),
        "waterfall_round_ns": round(wf_per_round_s * 1e9, 1),
        "waterfall_overhead_frac_of_solve": round(wf_overhead_frac, 6),
        "audited_round_wall_s": round(audited_wall, 4),
        "audit_twin_s": round(stats["audit"]["twin_s"], 4),
        "audit_verdicts": verdicts,
    }


def _ledger_percentile(vals: list, q: float) -> float:
    s = sorted(vals)
    if not s:
        return 0.0
    idx = min(int(round(q * (len(s) - 1))), len(s) - 1)
    return s[idx]


def _ledger_rounds_summary(seq0: int) -> dict:
    """Summarize the round-ledger records a stage produced (everything
    past ``seq0``): counts by mode + p50/p95 of the per-phase seconds.
    The flight recorder is always on, so this is a free by-product of
    the solves the stage already ran (ISSUE 12)."""
    from karpenter_tpu.obs import ledger as obs_ledger

    recs = [r for r in obs_ledger.LEDGER.since(seq0) if r.get("source") == "local"]
    out: dict = {
        "n": len(recs),
        "modes": {},
    }
    for r in recs:
        m = r.get("mode", "?")
        out["modes"][m] = out["modes"].get(m, 0) + 1
    for key in ("wall_s", "encode_s", "device_s", "decode_s"):
        vals = [r[key] for r in recs if isinstance(r.get(key), (int, float))]
        if vals:
            out[key] = {
                "p50": round(_ledger_percentile(vals, 0.50), 4),
                "p95": round(_ledger_percentile(vals, 0.95), 4),
            }
    return out


def _print_rounds_report(detail: dict) -> None:
    """--report-rounds: the per-stage round-ledger digest — how many
    rounds the stage recorded, their mode mix, and p50/p95 per phase.
    The JSON line carries the same numbers under each stage's "rounds"
    key."""
    for stage, st in sorted(detail.items()):
        if not isinstance(st, dict):
            continue
        rd = st.get("rounds")
        if not isinstance(rd, dict):  # --steady: "rounds" is the trace length
            rd = st.get("ledger_rounds")
        if not isinstance(rd, dict) or "modes" not in rd:
            continue
        modes = ",".join(f"{m}={n}" for m, n in sorted(rd["modes"].items()))
        phases = " ".join(
            f"{key[:-2]}=p50:{rd[key]['p50']:.4f}/p95:{rd[key]['p95']:.4f}"
            for key in ("wall_s", "encode_s", "device_s", "decode_s")
            if key in rd
        )
        print(f"rounds {stage:>28s}: n={rd['n']:<4d} [{modes}] {phases}")


def _print_padding_report(detail: dict) -> None:
    """--report-padding: per-solve padded-vs-real element waste, one line
    per (stage, axis). The JSON line still carries the same numbers under
    each stage's "padding" key; this is the human-readable view."""
    for stage, st in sorted(detail.items()):
        if not isinstance(st, dict) or "padding" not in st:
            continue
        for axis, w in sorted(st["padding"].items()):
            print(
                f"padding {stage:>28s} {axis:>14s}: "
                f"real={w['real']:>8d} padded={w['padded']:>8d} "
                f"waste={100.0 * w['waste_frac']:5.1f}%"
            )


def _print_shard_report(detail: dict) -> None:
    """--report-shard: per-stage mesh extents + dp merge outcomes by
    family + verdict-fetch bytes + the host-sync wall breakdown (time
    blocked on the per-round verdict fetch vs overlapped with dispatch
    and the pipelined decode). The JSON line carries the same numbers
    under each stage's "shard" key."""
    for stage, st in sorted(detail.items()):
        if not isinstance(st, dict):
            continue
        sh = st.get("shard")
        cov = st.get("coverage") or (sh or {}).get("coverage")
        if not sh and not cov:
            continue
        if sh:
            print(
                f"shard {stage:>28s}: mesh={sh['dp']}x{sh['it']} "
                f"rounds={sh['merge_rounds']} committed={sh['groups_committed']} "
                f"replayed={sh['groups_replayed']} "
                f"replicated_kb={sh['replicated_bytes'] / 1024:.1f}"
            )
        else:
            # zero dp merge rounds ran — say so explicitly instead of
            # omitting the stage (the coverage table below still shows
            # which families took the sequential path, with dp: 0)
            print(
                f"shard {stage:>28s}: dp: 0 (no dp merge rounds ran; "
                "sequential path only)"
            )
        fams = (sh or {}).get("families")
        if fams:
            fam_str = " ".join(
                f"{f}={v['committed']}c/{v['replayed']}r"
                for f, v in sorted(fams.items())
            )
            blocked = sh.get("sync_blocked_s", 0.0)
            overlapped = max(sh.get("merge_wall_s", 0.0) - blocked, 0.0)
            print(
                f"      {'':>28s}  families: {fam_str}  "
                f"verdicts={sh.get('verdict_fetches', 0)} "
                f"({sh.get('verdict_bytes', 0)}B fetched) "
                f"sync_blocked={blocked * 1000:.1f}ms "
                f"overlapped={overlapped * 1000:.1f}ms"
            )
        eff = (sh or {}).get("speculation_efficiency")
        if eff:
            eff_str = " ".join(
                f"{f}={v:.2f}" for f, v in sorted(eff.items())
            )
            util = {
                k[len("dp_rows_"):]: (sh or {}).get(k, 0)
                for k in ("dp_rows_committed", "dp_rows_replayed", "dp_rows_idle")
            }
            print(
                f"      {'':>28s}  dp rows: committed={util['committed']} "
                f"replayed={util['replayed']} idle={util['idle']}  "
                f"speculation efficiency (committed/dispatched pod-s): {eff_str}"
            )
        # per-family speculation coverage (ISSUE 14): what fraction of
        # each family's chunk groups entered a dp fan-out round vs stayed
        # on the ordered scan — the stage-aggregated counters when the
        # child reports them, else this solve's own routing ledger.
        # Families that never entered a dp round print an explicit dp: 0
        # with their sequential count instead of being omitted.
        if cov:
            parts = []
            for f, v in sorted(cov.items()):
                total = v["dp"] + v["sequential"]
                if not total:
                    # the run never routed this family at all — an em
                    # dash, not 0/0 (nan); bench_diff's coverage ratchet
                    # skips it too (ISSUE 20 satellite)
                    parts.append(f"{f}=—")
                elif not v["dp"]:
                    parts.append(f"{f}=dp:0/seq:{v['sequential']}")
                else:
                    parts.append(
                        f"{f}={v['dp']}/{total} ({100.0 * v['dp'] / total:.0f}%)"
                    )
            print(
                f"      {'':>28s}  dp coverage (groups dp/total): "
                + " ".join(parts)
            )


def _print_scan_report(detail: dict) -> None:
    """--report-scan: claims-axis occupancy per stage — the active window
    vs the live high-water, frozen-bank size, spill and compaction counts.
    The JSON line carries the same numbers under each stage's "scan" key."""
    for stage, st in sorted(detail.items()):
        if not isinstance(st, dict) or "scan" not in st:
            continue
        s = st["scan"]
        print(
            f"scan {stage:>28s}: window={s['window']:>5d}/{s['n_claims']:<5d} "
            f"live_hw={s['live_hw']:>5d} opened={s['n_open']:>5d} "
            f"frozen={s['frozen']:>5d} spills={s['spills']} "
            f"compactions={s['compactions']}"
        )


def main() -> None:
    import argparse

    parser = argparse.ArgumentParser(description="karpenter-tpu scheduler bench")
    parser.add_argument(
        "--report-padding",
        action="store_true",
        help="print per-solve padded-vs-real element waste per stage/axis "
        "(the same numbers land under each stage's 'padding' key in the "
        "final JSON line)",
    )
    parser.add_argument(
        "--report-scan",
        action="store_true",
        help="print per-stage claims-axis occupancy (active window vs live "
        "high-water, frozen bank, spills, compactions; the same numbers "
        "land under each stage's 'scan' key in the final JSON line)",
    )
    parser.add_argument(
        "--report-shard",
        action="store_true",
        help="print per-stage mesh-shard records (dp×it extents, merge "
        "rounds, committed/replayed chunk groups, replicated-bytes "
        "estimate; the same numbers land under each stage's 'shard' key "
        "in the final JSON line)",
    )
    parser.add_argument(
        "--report-rounds",
        action="store_true",
        help="print the per-stage round-ledger digest (round count, mode "
        "mix, p50/p95 wall/encode/device/decode seconds; the same numbers "
        "land under each stage's 'rounds' key in the final JSON line)",
    )
    parser.add_argument(
        "--steady",
        action="store_true",
        help="steady-state mode (ISSUE 7): run ONLY the resident-solver "
        "Poisson arrival/departure trace at 16k resident pods / 64-pod "
        "deltas and report sustained pods/sec + per-delta latency "
        "percentiles + the >= 5x p95 gate vs forced full re-solves",
    )
    parser.add_argument(
        "--steady-rounds", type=int, default=12,
        help="delta rounds in the --steady trace",
    )
    parser.add_argument(
        "--steady-rate", type=int, default=64,
        help="Poisson arrival rate (pods per delta round) for --steady",
    )
    parser.add_argument(
        "--steady-seed", type=int, default=0,
        help="trace RNG seed for --steady",
    )
    parser.add_argument(
        "--chaos",
        action="store_true",
        help="smoke mode: run ONLY the north-star scenario under a light "
        "fault plan and assert the wall gate still holds + the fault "
        "points' disabled-path overhead is < 1%% of a solve",
    )
    parser.add_argument(
        "--baseline",
        metavar="PATH",
        default=None,
        help="self-diff this run's final JSON against a committed bench "
        "JSON (BENCH_*.json) segment-by-segment via the "
        "karpenter_tpu.obs.bench_diff sentinel; any timing leaf past "
        "KTPU_BENCH_DIFF_THRESHOLD (default 25%%) makes the bench exit "
        "non-zero",
    )
    parser.add_argument(
        "--fleet",
        action="store_true",
        help="fleet chaos mode (ISSUE 16): two in-process solver replicas "
        "on a shared guardrail bus; kill replica A mid-stream under a "
        "seeded Poisson trace and report failover p95 per-delta latency, "
        "capsule-handoff counts, and quarantine propagation",
    )
    parser.add_argument(
        "--trace-out",
        metavar="PATH",
        default=None,
        help="with --fleet: write the stitched chaos-phase Perfetto JSON "
        "(one track per replica, handoffs as flow arrows) to PATH — "
        "openable at https://ui.perfetto.dev",
    )
    parser.add_argument(
        "--guard",
        action="store_true",
        help="guardrails mode (ISSUE 10): assert the disabled-audit gates "
        "cost < 1%% of a solve, then run one resident delta round at "
        "KTPU_GUARD_AUDIT_RATE=1.0 and report the shadow twin's cost",
    )
    args = parser.parse_args()

    from karpenter_tpu.utils.accel import force_cpu_if_unavailable

    fallback = force_cpu_if_unavailable()
    if fallback:
        reason = {
            "timeout": "accelerator init timed out",
            "absent": "no accelerator attached",
            "error": "accelerator probe crashed",
        }[fallback]
        print(json.dumps({"warning": f"{reason}; benchmarking on CPU"}))
    import jax

    platform = jax.devices()[0].platform
    on_tpu = platform != "cpu"

    if args.steady:
        print(
            json.dumps(
                {
                    "metric": "resident_steady_state",
                    "platform": platform,
                    "detail": run_steady_stage(
                        resident_pods=16384,
                        delta_pods=args.steady_rate,
                        rounds=args.steady_rounds,
                        seed=args.steady_seed,
                    ),
                }
            )
        )
        return

    if args.chaos:
        print(
            json.dumps(
                {
                    "metric": "chaos_smoke",
                    "platform": platform,
                    "detail": run_chaos_stage(on_tpu),
                }
            )
        )
        return

    if args.fleet:
        print(
            json.dumps(
                {
                    "metric": "fleet_chaos",
                    "platform": platform,
                    "detail": run_fleet_stage(
                        seed=args.steady_seed, trace_out=args.trace_out
                    ),
                }
            )
        )
        return

    if args.guard:
        print(
            json.dumps(
                {
                    "metric": "guard_smoke",
                    "platform": platform,
                    "detail": run_guard_stage(on_tpu),
                }
            )
        )
        return

    detail = {"platform": platform}

    # stage 1: selectors-only (round-1-comparable), with the Go-FFD
    # density check on the record
    detail["selectors_2048x400"] = run_stage(
        selector_pods(2048), 400, 256, host_parity=True
    )

    # stage 2: the reference mix — the headline number; a failure degrades
    # to smaller (distinct) sizes instead of killing the bench.
    # Host-oracle parity defaults to a 4096-pod sample above that size:
    # the Python FFD oracle is O(pods x claims) and the anti-affinity
    # fifth opens ~P/5 claims, so the full 16k host run costs ~30min
    # (KTPU_BENCH_FULL_HOST=1 runs it anyway, for the record).
    import os as _os

    full_host = _os.environ.get("KTPU_BENCH_FULL_HOST") == "1"
    host_cap = 10**9 if full_host else 4096
    sizes = [(16384, 4096)] if on_tpu else []
    sizes += [(4096, 1024), (1024, 256)]
    headline, mix_p = None, None
    for p, claims in sizes:
        try:
            headline = run_stage(
                mixed_pods(p), 400, claims, host_parity=(p <= host_cap)
            )
            mix_p = p
            break
        except Exception as e:  # noqa: BLE001 — record, shrink, continue
            detail[f"mixed_{p}x400_error"] = repr(e)[:300]
    if headline is None:
        raise RuntimeError(f"all mixed-stage sizes failed: {detail}")
    detail[f"mixed_{mix_p}x400"] = headline
    if mix_p > host_cap:
        # density adjudicated on a 4096 sample of the same distribution
        try:
            detail["mixed_density_4096_sample"] = {
                k: v
                for k, v in run_stage(
                    mixed_pods(4096), 400, 1024, warm_runs=0, host_parity=True
                ).items()
                if k in ("nodes", "host_nodes", "total_price_per_hour",
                         "host_price_per_hour", "density_parity", "host_wall_s",
                         "host_rss_mb", "cpu_s")
            }
        except Exception as e:  # noqa: BLE001
            detail["mixed_density_4096_sample"] = f"failed: {repr(e)[:300]}"

    # stage 3: north-star scale probe (BASELINE config #5 workload);
    # CPU fallback skips it — the un-accelerated scan takes ~minutes.
    # Density is adjudicated on a 10k subsample (the full 100k host oracle
    # would dominate the bench wall-clock).
    if on_tpu:
        try:
            # warm_runs=2: the first warm solve may re-size the claims axis
            # to the observed need (a one-time recompile, served from the
            # persistent cache thereafter); best-of reflects steady state
            detail["northstar_100000x1000"] = run_stage(
                selector_pods(100_000), 1000, 4096, warm_runs=2
            )
            detail["northstar_density_10000_sample"] = {
                k: v
                for k, v in run_stage(
                    selector_pods(10_000), 1000, 1024, warm_runs=0, host_parity=True
                ).items()
                if k in ("nodes", "host_nodes", "total_price_per_hour",
                         "host_price_per_hour", "density_parity", "host_wall_s",
                         "host_rss_mb", "cpu_s")
            }
        except Exception as e:  # noqa: BLE001
            detail["northstar_100000x1000"] = f"failed: {repr(e)[:300]}"
    else:
        detail["northstar_100000x1000"] = "skipped on CPU fallback"

    # stage 3.1: per-shard record — the (dp × it) mesh path in a child
    # with 8 virtual CPU devices, so the default bench always carries a
    # "shard" stage JSON (ISSUE 8)
    try:
        detail["shard_8192x200"] = run_shard_stage()
    except Exception as e:  # noqa: BLE001
        detail["shard_8192x200"] = f"failed: {repr(e)[:300]}"

    # stage 3.2: the 1M × 1000 north star as a per-shard problem
    # (ISSUE 8). TPU-gated: the un-accelerated 1M scan takes tens of
    # minutes; KTPU_BENCH_1M=1 forces it for offline CPU runs.
    import os as _os

    if on_tpu or _os.environ.get("KTPU_BENCH_1M") == "1":
        try:
            import jax as _jax

            from karpenter_tpu.parallel import make_mesh as _make_mesh

            mesh_1m = _make_mesh() if _jax.device_count() > 1 else None
            detail["northstar_1000000x1000"] = run_1m_stage(on_tpu, mesh=mesh_1m)
        except Exception as e:  # noqa: BLE001
            detail["northstar_1000000x1000"] = f"failed: {repr(e)[:300]}"
    else:
        detail["northstar_1000000x1000"] = (
            "skipped (TPU-gated; KTPU_BENCH_1M=1 forces on CPU)"
        )

    # stage 3.5: gang-storm — all-or-nothing slice scheduling throughput
    # (gangs-scheduled/sec + atomic spill accounting, ISSUE 6)
    try:
        detail["gang_storm"] = run_gang_storm_stage(on_tpu)
    except Exception as e:  # noqa: BLE001
        detail["gang_storm"] = f"failed: {repr(e)[:300]}"

    # stage 3.75: resident incremental solver — steady-state deltas vs
    # forced full re-solves (ISSUE 7; `--steady` runs the full-size gate)
    try:
        detail["steady_4096x64"] = run_steady_stage(
            resident_pods=4096, rounds=8, full_sample=2, max_claims=4096
        )
    except Exception as e:  # noqa: BLE001
        detail["steady_4096x64"] = f"failed: {repr(e)[:300]}"

    # stage 3.9: placement objectives — per-policy fleet price on one
    # mixed-generation multi-pool problem, with the in-stage hard gate
    # cost_min <= lexical (ISSUE 19)
    try:
        detail["objectives_192x48"] = run_objective_stage()
    except Exception as e:  # noqa: BLE001
        detail["objectives_192x48"] = f"failed: {repr(e)[:300]}"

    # stage 4: disruption what-ifs — batched vs sequential (§2.6)
    try:
        detail["whatif_batch"] = run_whatif_stage(100 if on_tpu else 16)
    except Exception as e:  # noqa: BLE001
        detail["whatif_batch"] = f"failed: {repr(e)[:300]}"

    # stage 5: gRPC solver-split overhead on the warm 2048 workload
    try:
        detail["rpc_2048x400"] = run_rpc_stage(
            selector_pods(2048), 400, detail["selectors_2048x400"]["wall_s"]
        )
    except Exception as e:  # noqa: BLE001
        detail["rpc_2048x400"] = f"failed: {repr(e)[:300]}"

    # stage 6: restart with a populated persistent compile cache — the
    # realistic "first batch after a controller restart" cost
    try:
        detail["restart_warm_cache_2048x400"] = run_restart_stage(
            2048, 400, 256, on_tpu=on_tpu
        )
    except Exception as e:  # noqa: BLE001
        detail["restart_warm_cache_2048x400"] = f"failed: {repr(e)[:300]}"

    # the TPU-regime regression gate (VERDICT r3 #4, ratcheted to round-5
    # reality per VERDICT r5 directive #3): the same threshold is enforced
    # as a test when a TPU is attached (tests/test_perf_gate.py)
    if on_tpu:
        detail["tpu_regime_gate"] = {
            "threshold_pods_per_sec": 8000.0,
            "measured": detail["selectors_2048x400"]["pods_per_sec"],
            "ok": detail["selectors_2048x400"]["pods_per_sec"] >= 8000.0,
        }

    # whole-process envelope: where the control plane + solver client ended
    # up after every stage (the e2e thresholds' analog of a final scrape)
    from karpenter_tpu.envelope.sampler import read_cpu_seconds, read_rss_bytes

    detail["host_envelope"] = {
        "final_rss_mb": round(read_rss_bytes() / 2**20, 1),
        "total_cpu_s": round(read_cpu_seconds(), 1),
    }

    if args.report_padding:
        _print_padding_report(detail)
    if args.report_scan:
        _print_scan_report(detail)
    if args.report_shard:
        _print_shard_report(detail)
    if args.report_rounds:
        _print_rounds_report(detail)

    doc = {
        "metric": f"scheduler_throughput_{mix_p}pods_400types_refmix",
        "value": headline["pods_per_sec"],
        "unit": "pods/sec",
        "vs_baseline": round(headline["pods_per_sec"] / BASELINE_PODS_PER_SEC, 2),
        "detail": detail,
    }

    if args.baseline:
        # the perf-regression sentinel (ISSUE 15): diff this run's JSON
        # against the committed baseline segment-by-segment and ratchet
        from karpenter_tpu.obs import bench_diff as obs_bench_diff

        with open(args.baseline) as fh:
            base_doc = json.load(fh)
        bd = obs_bench_diff.diff_docs(base_doc, doc)
        for line in obs_bench_diff.format_report(bd, args.baseline, "this run"):
            print(line)
        doc["baseline_diff"] = {
            "baseline": args.baseline,
            "threshold": bd["threshold"],
            "regressions": [r["path"] for r in bd["regressions"]],
            "ok": not bd["regressions"],
        }

    print(json.dumps(doc))
    if args.baseline and doc["baseline_diff"]["regressions"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
