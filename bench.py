"""Headline benchmark: scheduling throughput.

Mirrors the reference's in-process scheduler benchmark
(scheduling_benchmark_test.go): diverse pods against a fake catalog with
the reference's 1/5 mix — generic, TSC-zone, TSC-hostname, pod-affinity,
pod-anti-affinity (makeDiversePods, :259-272) — through the full pipeline:
host encode, device scan-FFD solve, host decode to claims.

Stages (sizes scale down on CPU fallback so the bench stays bounded):
  1. selectors-only 2048 x 400   — round-1-comparable number
  2. reference mix (headline)    — 16384 x 400 on TPU / 4096 x 400 on CPU
  3. north-star scale probe      — 100k x 1k selector mix (TPU only;
                                    BASELINE.json config #5 workload)

Prints ONE final JSON line:
  {"metric": ..., "value": N, "unit": "pods/sec", "vs_baseline": N/100,
   "detail": {per-stage wall/encode/device/decode splits, platform}}
"""

from __future__ import annotations

import json
import time

BASELINE_PODS_PER_SEC = 100.0  # reference MinPodsPerSec gate (:58)


def selector_pods(n):
    import numpy as np

    from karpenter_tpu.models import labels as l
    from karpenter_tpu.models.pod import make_pod

    rng = np.random.default_rng(0)
    zones = ("test-zone-1", "test-zone-2", "test-zone-3", "test-zone-4")
    pods = []
    for i in range(n):
        sel = {}
        if i % 5 == 1:
            sel[l.LABEL_TOPOLOGY_ZONE] = zones[i % len(zones)]
        if i % 5 == 2:
            sel[l.LABEL_ARCH] = l.ARCH_AMD64
        if i % 5 == 3:
            sel[l.CAPACITY_TYPE_LABEL_KEY] = l.CAPACITY_TYPE_ON_DEMAND
        pods.append(
            make_pod(
                f"p-{i}",
                cpu=float(rng.choice([0.1, 0.25, 0.5, 1.0, 2.0, 4.0])),
                memory=f"{rng.choice([0.25, 0.5, 1.0, 2.0, 4.0])}Gi",
                node_selector=sel,
            )
        )
    return pods


def mixed_pods(n):
    """The reference benchmark's makeDiversePods: equal fifths of generic,
    TSC-zone, TSC-hostname, zone pod-affinity, hostname pod-anti-affinity
    (all anti pods share one label, scheduling_benchmark_test.go:274-300)."""
    import numpy as np

    from karpenter_tpu.models import labels as l
    from karpenter_tpu.models.pod import (
        PodAffinityTerm,
        TopologySpreadConstraint,
        make_pod,
    )

    rng = np.random.default_rng(0)
    pods = []
    for i in range(n):
        p = make_pod(
            f"p-{i}",
            cpu=float(rng.choice([0.1, 0.25, 0.5, 1.0, 2.0])),
            memory=f"{rng.choice([0.25, 0.5, 1.0, 2.0])}Gi",
        )
        kind = i % 5
        if kind == 1:
            p.metadata.labels = {"spread": "zonal"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=l.LABEL_TOPOLOGY_ZONE,
                    label_selector={"spread": "zonal"},
                )
            ]
        elif kind == 2:
            p.metadata.labels = {"spread": "host"}
            p.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=l.LABEL_HOSTNAME,
                    label_selector={"spread": "host"},
                )
            ]
        elif kind == 3:
            p.metadata.labels = {"aff": "group"}
            p.spec.pod_affinity = [
                PodAffinityTerm(
                    topology_key=l.LABEL_TOPOLOGY_ZONE, label_selector={"aff": "group"}
                )
            ]
        elif kind == 4:
            p.metadata.labels = {"app": "nginx"}
            p.spec.pod_anti_affinity = [
                PodAffinityTerm(
                    topology_key=l.LABEL_HOSTNAME, label_selector={"app": "nginx"}
                )
            ]
        pods.append(p)
    return pods


def run_stage(pods, n_types, max_claims, warm_runs=2):
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.controllers.provisioning import TPUScheduler, build_templates
    from karpenter_tpu.models.nodepool import NodePool

    pool = NodePool()
    pool.metadata.name = "default"
    templates = build_templates([(pool, instance_types(n_types))])
    sched = TPUScheduler(templates, pod_pad=len(pods), max_claims=max_claims)
    t0 = time.perf_counter()
    result = sched.solve(pods)  # cold: compile + run
    cold_s = time.perf_counter() - t0
    assert not result.unschedulable, f"{len(result.unschedulable)} unschedulable"
    best, timings = None, dict(sched.last_timings)
    for _ in range(warm_runs):
        t0 = time.perf_counter()
        result = sched.solve(pods)
        wall = time.perf_counter() - t0
        if best is None or wall < best:
            best, timings = wall, dict(sched.last_timings)
    best = best if best is not None else cold_s
    return {
        "pods": len(pods),
        "types": n_types,
        "pods_per_sec": round(len(pods) / best, 1),
        "wall_s": round(best, 4),
        "cold_s": round(cold_s, 2),  # includes XLA compile
        "encode_s": round(timings["encode_s"], 4),
        "device_s": round(timings["device_s"], 4),
        "decode_s": round(timings["decode_s"], 4),
        "nodes": result.node_count,
        "total_price_per_hour": round(result.total_price(), 2),
    }


def main() -> None:
    from karpenter_tpu.utils.accel import force_cpu_if_unavailable

    fallback = force_cpu_if_unavailable()
    if fallback:
        reason = {
            "timeout": "accelerator init timed out",
            "absent": "no accelerator attached",
            "error": "accelerator probe crashed",
        }[fallback]
        print(json.dumps({"warning": f"{reason}; benchmarking on CPU"}))
    import jax

    platform = jax.devices()[0].platform
    on_tpu = platform != "cpu"

    detail = {"platform": platform}

    # stage 1: selectors-only (round-1-comparable)
    detail["selectors_2048x400"] = run_stage(selector_pods(2048), 400, 256)

    # stage 2: the reference mix — the headline number; a failure degrades
    # to smaller (distinct) sizes instead of killing the bench
    sizes = [(16384, 4096)] if on_tpu else []
    sizes += [(4096, 1024), (1024, 256)]
    headline, mix_p = None, None
    for p, claims in sizes:
        try:
            headline, mix_p = run_stage(mixed_pods(p), 400, claims), p
            break
        except Exception as e:  # noqa: BLE001 — record, shrink, continue
            detail[f"mixed_{p}x400_error"] = repr(e)[:300]
    if headline is None:
        raise RuntimeError(f"all mixed-stage sizes failed: {detail}")
    detail[f"mixed_{mix_p}x400"] = headline

    # stage 3: north-star scale probe (BASELINE config #5 workload);
    # CPU fallback skips it — the un-accelerated scan takes ~minutes
    if on_tpu:
        try:
            detail["northstar_100000x1000"] = run_stage(
                selector_pods(100_000), 1000, 4096, warm_runs=1
            )
        except Exception as e:  # noqa: BLE001
            detail["northstar_100000x1000"] = f"failed: {repr(e)[:300]}"
    else:
        detail["northstar_100000x1000"] = "skipped on CPU fallback"

    print(
        json.dumps(
            {
                "metric": f"scheduler_throughput_{mix_p}pods_400types_refmix",
                "value": headline["pods_per_sec"],
                "unit": "pods/sec",
                "vs_baseline": round(headline["pods_per_sec"] / BASELINE_PODS_PER_SEC, 2),
                "detail": detail,
            }
        )
    )


if __name__ == "__main__":
    main()
