"""Headline benchmark: scheduling throughput.

Mirrors the reference's in-process scheduler benchmark
(scheduling_benchmark_test.go: diverse pods against a 400-type fake
catalog, gate MinPodsPerSec = 100): packs 2048 mixed pods against 400
instance types through the full pipeline — host encode, device scan-FFD
solve, host decode to claims — and reports warm-path pods/sec.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "pods/sec", "vs_baseline": N/100}
"""

from __future__ import annotations

import json
import time

N_PODS = 2048
N_TYPES = 400
BASELINE_PODS_PER_SEC = 100.0  # reference MinPodsPerSec gate


def build_problem():
    import numpy as np

    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.controllers.provisioning import build_templates
    from karpenter_tpu.models import labels as l
    from karpenter_tpu.models.nodepool import NodePool
    from karpenter_tpu.models.pod import make_pod

    pool = NodePool()
    pool.metadata.name = "default"
    templates = build_templates([(pool, instance_types(N_TYPES))])
    rng = np.random.default_rng(0)
    pods = []
    zones = ("test-zone-1", "test-zone-2", "test-zone-3", "test-zone-4")
    for i in range(N_PODS):
        sel = {}
        # diverse mix: plain, zonal selectors, arch selectors
        if i % 5 == 1:
            sel[l.LABEL_TOPOLOGY_ZONE] = zones[i % len(zones)]
        if i % 5 == 2:
            sel[l.LABEL_ARCH] = l.ARCH_AMD64
        if i % 5 == 3:
            sel[l.CAPACITY_TYPE_LABEL_KEY] = l.CAPACITY_TYPE_ON_DEMAND
        pods.append(
            make_pod(
                f"p-{i}",
                cpu=float(rng.choice([0.1, 0.25, 0.5, 1.0, 2.0, 4.0])),
                memory=f"{rng.choice([0.25, 0.5, 1.0, 2.0, 4.0])}Gi",
                node_selector=sel,
            )
        )
    return templates, pods


def main() -> None:
    from karpenter_tpu.utils.accel import force_cpu_if_unavailable

    fallback = force_cpu_if_unavailable()
    if fallback:
        reason = {
            "timeout": "accelerator init timed out",
            "absent": "no accelerator attached",
            "error": "accelerator probe crashed",
        }[fallback]
        print(json.dumps({"warning": f"{reason}; benchmarking on CPU"}))
    import jax

    platform = jax.devices()[0].platform

    from karpenter_tpu.controllers.provisioning import TPUScheduler

    templates, pods = build_problem()
    sched = TPUScheduler(templates, pod_pad=N_PODS, max_claims=256)
    result = sched.solve(pods)  # cold: compile + warmup
    assert not result.unschedulable, f"{len(result.unschedulable)} unschedulable"

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        result = sched.solve(pods)
        times.append(time.perf_counter() - t0)
    best = min(times)
    pods_per_sec = N_PODS / best

    print(
        json.dumps(
            {
                "metric": f"scheduler_throughput_{N_PODS}pods_{N_TYPES}types",
                "value": round(pods_per_sec, 1),
                "unit": "pods/sec",
                "vs_baseline": round(pods_per_sec / BASELINE_PODS_PER_SEC, 2),
                "detail": {
                    "platform": platform,
                    "nodes": result.node_count,
                    "wall_s": round(best, 4),
                    "total_price_per_hour": round(result.total_price(), 2),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
